"""IngestPlane: the engine-process drainer of the multi-process plane.

One plane per engine. It owns every shared-memory segment (control
header, MPSC request ring, one SPSC response ring per worker slot) and
a drainer thread that:

* pops request frames, decodes the columns, and rides admissions
  through the SAME columnar spine the batch window uses — grouped
  ``submit_bulk`` with per-request ts/acquire columns, per-request
  ``submit_entry`` fallback for the rule classes bulk declines
  (cluster mode, THREAD-grade param rules, collection values) — so
  worker-path verdicts are bit-identical to the in-process oracle;
* fans speculative fast-tier verdicts back WITHOUT waiting for the
  settling flush (``entry_windowed`` parity: the device settles on the
  tier's own cadence);
* reconstructs each row's packed W3C traceparent and records
  per-request admission traces (PR-4 identity survives the process
  boundary);
* keeps the **live-admission ledger** per worker: every admitted
  THREAD-charged row is recorded so a dead worker's heartbeat (stale
  past ``sentinel.tpu.ipc.worker.dead.ms``) triggers an auto-exit of
  exactly its live admissions — device and mirror THREAD gauges return
  to exactly 0, the plane's analog of the batch window's
  abandoned-entry release;
* publishes the engine heartbeat + health word and the per-resource
  fail-open/closed failover-policy snapshot into the control header —
  what workers serve from when this process dies;
* folds worker-side ring-full shed counts into the engine's
  IngestValve accounting (cause ``ring``) so shedding stays one
  fleet-visible number.

Nothing here touches the engine submit hot path: a disabled plane is
never constructed, and an enabled one costs the engine exactly the
work the frames carry.
"""

from __future__ import annotations

import multiprocessing
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from sentinel_tpu.core import errors as E
from sentinel_tpu.ipc import frames as fr
from sentinel_tpu.ipc.ring import (
    HEALTH_CLOSED,
    HEALTH_DEGRADED,
    HEALTH_HANDOFF,
    HEALTH_HEALTHY,
    ControlBlock,
    ShmRing,
    resolve_spin_us,
)
from sentinel_tpu.ipc.worker import PlaneChannel
from sentinel_tpu.metrics.spans import get_journal
from sentinel_tpu.metrics.spans import wall_ms as _span_wall_ms
from sentinel_tpu.utils.config import config


class _WorkerState:
    """Engine-side per-worker-slot state: the intern decode table and
    the live-admission ledger."""

    __slots__ = (
        "names", "live", "last_epoch", "last_seen", "shed_seen", "attached",
    )

    def __init__(self) -> None:
        self.names: Dict[int, str] = {}
        # (rows, resource, speculative, acquire) -> live admitted count.
        self.live: Dict[Tuple[tuple, str, bool, int], int] = {}
        self.last_epoch = 0
        self.last_seen = 0.0
        self.shed_seen = 0
        self.attached = False


class IngestPlane:
    """Engine-scoped multi-process ingest plane (see module doc)."""

    def __init__(self, engine, start: bool = True, handles=None) -> None:
        self._engine = engine
        self.workers_max = max(1, config.get_int(config.IPC_WORKERS_MAX, 8))
        self.ring_slots = config.get_int(config.IPC_RING_SLOTS, 1024)
        self.slot_bytes = max(
            1024, config.get_int(config.IPC_SLOT_BYTES, 16384)
        )
        self.resp_slots = config.get_int(config.IPC_RESP_SLOTS, 1024)
        self.worker_dead_ms = max(
            1, config.get_int(config.IPC_WORKER_DEAD_MS, 1000)
        )
        self.heartbeat_ms = max(1, config.get_int(config.IPC_HEARTBEAT_MS, 100))
        self.poll_us = max(10, config.get_int(config.IPC_POLL_US, 200))
        # Adaptive wakeups (sentinel.tpu.ipc.wakeup=adaptive): the
        # drainer spins briefly then parks on a doorbell semaphore a
        # publishing producer rings; "sleep" (the default) keeps the
        # PR-13 sleep-poll backoff exactly (no semaphores exist).
        wake = (config.get(config.IPC_WAKEUP) or "sleep").strip().lower()
        self.adaptive_wakeup = wake == "adaptive"
        self.spin_s = resolve_spin_us(
            config.get_int(config.IPC_WAKEUP_SPIN_US, -1)
        ) / 1e6
        self.park_s = max(
            1, config.get_int(config.IPC_WAKEUP_PARK_MS, 5)
        ) / 1e3
        self._mp = multiprocessing.get_context("spawn")
        # Named segments (sentinel.tpu.ipc.shm.prefix / supervisor
        # handles): a deterministic prefix lets a RESTARTED engine
        # process re-attach to the EXISTING rings — workers keep their
        # mappings, nothing re-spawns. "" (the default) keeps the
        # anonymous PR-13/14 segments exactly. The producer claim lock
        # and doorbells cannot live in shared memory; in supervised
        # mode they come from the SUPERVISOR's handles (so they outlive
        # any one engine process), otherwise this plane creates its own
        # — an unsupervised re-attach then must not add NEW producers
        # through channel() while old workers still hold the old lock.
        if handles is not None:
            self.prefix = (handles.prefix or "").strip()
            self._req_lock = handles.request_lock
            self._req_doorbell = (
                handles.request_doorbell if self.adaptive_wakeup else None
            )
            self._handle_bells = list(handles.response_doorbells or [])
        else:
            self.prefix = (config.get(config.IPC_SHM_PREFIX) or "").strip()
            self._req_lock = self._mp.Lock()
            self._req_doorbell = (
                self._mp.Semaphore(0) if self.adaptive_wakeup else None
            )
            self._handle_bells = None
        self.attached = False
        # Who unlinks the named segments at close: a handles-mode
        # (supervised) plane NEVER does — the rings must outlive this
        # engine process for the next one to re-attach warm; the
        # SUPERVISOR unlinks at final shutdown
        # (supervise.unlink_segments). A prefix-without-handles plane
        # owns them like the anonymous case.
        self._own_segments = handles is None
        if self.prefix:
            ctl_name = f"{self.prefix}-ctl"
            try:
                self.control = ControlBlock(ctl_name, self.workers_max)
                self.attached = True
            except FileNotFoundError:
                try:
                    self.control = ControlBlock(
                        ctl_name, self.workers_max, create=True
                    )
                except FileExistsError:
                    self.control = ControlBlock(ctl_name, self.workers_max)
                    self.attached = True
            self.control._owner = self._own_segments
            self.request = self._attach_or_create_ring(
                f"{self.prefix}-req", self.ring_slots,
                lock=self._req_lock, doorbell=self._req_doorbell,
            )
        else:
            self.control = ControlBlock(None, self.workers_max, create=True)
            self.request = ShmRing(
                None, self.ring_slots, self.slot_bytes, create=True,
                lock=self._req_lock, doorbell=self._req_doorbell,
            )
        # Response rings allocate LAZILY at channel() time: eagerly
        # mapping workers_max rings would hold ~workers_max x
        # resp_slots x slot_bytes of /dev/shm (~134 MB at defaults)
        # for worker slots that may never attach.
        self.responses: List[Optional[ShmRing]] = [
            None for _ in range(self.workers_max)
        ]
        self._resp_doorbells: List[Optional[object]] = [
            None for _ in range(self.workers_max)
        ]
        self._workers: List[_WorkerState] = [
            _WorkerState() for _ in range(self.workers_max)
        ]
        self._lock = threading.Lock()
        self.counters: Dict[str, int] = {
            "frames": 0, "requests": 0, "bulk_rows": 0, "exits": 0,
            "exits_unpaired": 0, "worker_sheds": 0, "decode_drops": 0,
            "worker_deaths": 0, "auto_exits": 0, "responses_dropped": 0,
            "stalled_skips": 0, "worker_reconnects": 0, "reasserts": 0,
            "stale_frames": 0,
        }
        self._policy_published: Optional[str] = None
        # Fleet span journal: per-frame drain spans on the same
        # wall-ms ruler this plane's control header publishes.
        self._spans = get_journal("engine")
        self._last_sweep = 0.0
        # World generation: bumped by on_engine_reset so a decision
        # batch that STARTED before a reset cannot insert ledger
        # entries for the dead world after the ledgers were dropped
        # (a later reap would release them against fresh gauges).
        self._world = 0
        # Worker ids handed out by claim_worker_slots but not yet seen
        # attached — keeps a second run_workers from reusing a slot
        # whose child is still booting.
        self._claimed: set = set()
        self._stop = threading.Event()
        self.closed = False
        # Planned-handoff drain (handoff()): while set, the control
        # heartbeat publishes HANDOFF — workers hold new admissions for
        # the successor instead of serving policy verdicts.
        self._handoff = False
        self._thread: Optional[threading.Thread] = None
        self._ctrl: Optional[threading.Thread] = None
        # The intern generation starts at 1 so a worker attaching to a
        # RESTARTED plane under recycled shm names can never alias
        # generation 0 reads from the zeroed header.
        self.control.bump_intern_gen()
        # Hot-restart generation: one bump per plane attach/create —
        # workers react to the change with the reconnect protocol
        # (re-intern, ledger re-assert, buffered-exit replay).
        self.engine_epoch = self.control.bump_engine_boot()
        # Engine pid for the worker-side death-confirmation probe
        # (dead.confirm.ms): a stale wall clock + a live pid means
        # pegged-not-dead.
        import os as _os

        self.control.set_engine_pid(_os.getpid())
        # Frames still in a re-attached ring belong to the DEAD world:
        # their callers were policy-served long ago and their intern ids
        # mean nothing here — drop anything below the post-attach
        # generation instead of guessing (fresh planes never gate).
        self._min_gen = self.control.intern_gen() if self.attached else 0
        if self.attached:
            # Shed-fold baselines: the control slots carry each worker's
            # CUMULATIVE shed count from the old world — folding from 0
            # would recount every old shed into the new engine's valve.
            for wid in range(self.workers_max):
                try:
                    _e, _w, pid, shed = self.control.worker_view(wid)
                except (ValueError, TypeError):
                    continue
                if pid != 0:
                    self._workers[wid].shed_seen = shed
        engine.ipc_plane = self
        if start:
            self.start()

    def _attach_or_create_ring(self, name, slots, lock=None, doorbell=None):
        try:
            ring = ShmRing(
                name, slots, self.slot_bytes, lock=lock, doorbell=doorbell
            )
        except FileNotFoundError:
            try:
                ring = ShmRing(
                    name, slots, self.slot_bytes, create=True, lock=lock,
                    doorbell=doorbell,
                )
            except FileExistsError:
                ring = ShmRing(
                    name, slots, self.slot_bytes, lock=lock,
                    doorbell=doorbell,
                )
        ring._owner = self._own_segments
        return ring

    # ------------------------------------------------------------------
    # attach surface
    # ------------------------------------------------------------------
    def claim_worker_slots(self, n: int) -> List[int]:
        """Reserve ``n`` free worker ids for a spawner (the
        ``api.run_workers`` allocation): a slot is free when no live
        worker is attached, its control slot is clear, and no earlier
        claim is still pending attach. Without this, a second
        run_workers on the same engine would reuse ids 0..n-1 — two
        clients on one response ring race its tail pointer and each
        steals half the other's verdicts."""
        out: List[int] = []
        with self._lock:
            for wid in range(self.workers_max):
                if len(out) == n:
                    break
                ws = self._workers[wid]
                if ws.attached or wid in self._claimed:
                    continue
                try:
                    _epoch, _wall, pid, _shed = self.control.worker_view(wid)
                except (ValueError, TypeError):
                    continue
                if pid != 0:
                    continue
                out.append(wid)
            if len(out) < n:
                raise ValueError(
                    f"claim_worker_slots: only {len(out)} of {n} worker "
                    f"slots free (workers.max={self.workers_max}; stopped "
                    "workers free their slots at the dead-worker sweep)"
                )
            self._claimed.update(out)
        return out

    def _ensure_response_locked(self, worker_id: int):
        """The worker's SPSC response ring, created (or, in named mode,
        re-attached after a hot-restart) lazily; caller holds
        ``self._lock``."""
        if self.responses[worker_id] is not None:
            return self.responses[worker_id]
        bell = None
        if self.adaptive_wakeup:
            if self._handle_bells is not None and worker_id < len(
                self._handle_bells
            ):
                bell = self._handle_bells[worker_id]
            else:
                bell = self._mp.Semaphore(0)
        self._resp_doorbells[worker_id] = bell
        if self.prefix:
            ring = self._attach_or_create_ring(
                f"{self.prefix}-resp{worker_id}", self.resp_slots,
                doorbell=bell,
            )
        else:
            ring = ShmRing(
                None, self.resp_slots, self.slot_bytes, create=True,
                doorbell=bell,
            )
        self.responses[worker_id] = ring
        return ring

    def channel(self, worker_id: int) -> PlaneChannel:
        if not (0 <= worker_id < self.workers_max):
            raise ValueError(f"worker_id {worker_id} out of range")
        with self._lock:
            self._ensure_response_locked(worker_id)
            resp_name = self.responses[worker_id].name
            resp_bell = self._resp_doorbells[worker_id]
        return PlaneChannel(
            control_name=self.control.name,
            request_name=self.request.name,
            response_name=resp_name,
            ring_slots=self.ring_slots,
            slot_bytes=self.slot_bytes,
            resp_slots=self.resp_slots,
            workers_max=self.workers_max,
            request_lock=self._req_lock,
            request_doorbell=self._req_doorbell,
            response_doorbell=resp_bell,
        )

    def spawn_context(self):
        """The plane's (spawn) multiprocessing context — workers must
        be descendants of this process for the claim lock to travel."""
        return self._mp

    # ------------------------------------------------------------------
    # drainer
    # ------------------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._publish_control(force=True)
        self._thread = threading.Thread(
            target=self._run, name="sentinel-ipc-plane", daemon=True
        )
        self._thread.start()
        # Control-plane duties on their OWN thread: the drainer blocks
        # inside engine flushes (a first-compile runs for seconds), and
        # a heartbeat that rides the drain loop would starve exactly
        # then — workers would declare a merely-busy engine dead.
        self._ctrl = threading.Thread(
            target=self._control_loop, name="sentinel-ipc-control",
            daemon=True,
        )
        self._ctrl.start()

    def _run(self) -> None:
        idle_s = self.poll_us / 1e6
        delay = idle_s
        park = 0.0005
        # The park timeout is additionally capped by the heartbeat
        # cadence: the worker-death sweep rides this loop and must keep
        # its clock even when the doorbell never rings.
        park_cap = min(self.park_s, max(0.001, self.heartbeat_ms / 1e3))
        while not self._stop.is_set():
            try:
                worked = self._drain_once()
            except Exception:
                from sentinel_tpu.utils.record_log import record_log

                record_log.error("[IngestPlane] drain failed", exc_info=True)
                worked = False
            if worked:
                delay = idle_s
                park = 0.0005
            elif self.adaptive_wakeup:
                # Spin-then-park: bounded spin keeps the hot round trip
                # off the scheduler; the park (exponentially growing
                # timeout, producer-rung doorbell) bounds idle burn.
                self.request.wait_readable(self.spin_s, park)
                park = min(park * 2, park_cap)
            else:
                time.sleep(delay)
                delay = min(delay * 2, 0.002)

    def _control_loop(self) -> None:
        """Heartbeat + policy publishing ONLY: this thread must never
        block on engine work (a reap's flush can compile for seconds,
        and a starved heartbeat reads as engine death to every worker).
        The worker death sweep runs on the drainer, which is allowed to
        be busy."""
        while not self._stop.wait(self.heartbeat_ms / 1e3):
            try:
                self._publish_control()
            except Exception:
                from sentinel_tpu.utils.record_log import record_log

                record_log.error(
                    "[IngestPlane] control tick failed", exc_info=True
                )

    def _drain_once(self) -> bool:
        """One drainer iteration; True when any frame was processed."""
        now = time.monotonic()
        if (now - self._last_sweep) * 1e3 >= self.heartbeat_ms:
            self._last_sweep = now
            self._check_workers(now)
        payloads = self.request.pop_all(limit=128)
        if not payloads:
            if self.request.maybe_skip_stalled(self.worker_dead_ms / 1e3):
                self.counters["stalled_skips"] += 1
                return True
            return False
        eng = self._engine
        tele = eng.telemetry
        spj = self._spans
        t_drain = _span_wall_ms() if spj.enabled else 0.0
        frame_meta: Optional[List[tuple]] = [] if spj.enabled else None
        groups: Dict[tuple, list] = {}
        exits: List[tuple] = []
        responses: Dict[int, list] = {}
        n_rows = 0
        for payload in payloads:
            try:
                f = fr.decode_frame(payload)
            except (ValueError, fr.struct.error):
                self.counters["decode_drops"] += 1
                continue
            if not (0 <= f.worker_id < self.workers_max):
                self.counters["decode_drops"] += 1
                continue
            ws = self._workers[f.worker_id]
            if self._min_gen and f.intern_gen < self._min_gen:
                # Dead-world backlog: a frame pushed before THIS plane
                # attached (its engine died with it undrained). The
                # callers were policy-served long ago and the intern ids
                # belong to a table that died with the old process —
                # answer any still-parked waiter with a fast shed
                # rather than admitting ghosts into the new world.
                self.counters["stale_frames"] += 1
                if f.kind in (fr.KIND_ENTRY, fr.KIND_BULK):
                    out = responses.setdefault(f.worker_id, [])
                    for s in f.columns["seq"].tolist():
                        out.append((int(s), 0, E.BLOCK_SHED, 0, 0))
                continue
            ws.attached = True
            self._claimed.discard(f.worker_id)
            for iid, raw in f.interns:
                ws.names[iid] = raw.decode("utf-8", "surrogatepass")
            self._fold_sheds(f.worker_id, f.shed_count)
            self.counters["frames"] += 1
            if f.kind in (fr.KIND_ENTRY, fr.KIND_BULK):
                n_rows += f.n
                if frame_meta is not None and f.n:
                    s = f.columns["seq"]
                    frame_meta.append(
                        (f.worker_id, int(s[0]), int(s[f.n - 1]), int(f.n))
                    )
                self._collect_entries(f, ws, groups, responses)
            elif f.kind == fr.KIND_EXIT:
                self._collect_exits(f, ws, exits)
            elif f.kind == fr.KIND_REASSERT:
                self._apply_reasserts(f, ws)
        if n_rows:
            self.counters["requests"] += n_rows
            if tele.enabled:
                tele.note_ipc_frames(len(payloads), n_rows)
        self._apply_exits(exits)
        if groups:
            self._decide_groups(groups, responses)
        self._send_responses(responses)
        if spj.enabled:
            # One drain span for the batch plus one per entry/bulk
            # frame carrying the (wid, seq range) correlation key the
            # worker's admit spans point at. The frame spans share the
            # drain interval: dequeue happened at t_drain, the verdict
            # left with _send_responses.
            t_end = _span_wall_ms()
            dur = t_end - t_drain
            spj.record(
                "drain", "engine", t_drain, dur,
                frames=len(payloads), rows=n_rows,
            )
            for wid, lo, hi, n in frame_meta or ():
                spj.record(
                    "frame", "engine", t_drain, dur,
                    wid=wid, seq_lo=lo, seq_hi=hi, rows=n,
                )
        return True

    # -- decode helpers -------------------------------------------------
    def _name(self, ws: _WorkerState, iid: int) -> Optional[str]:
        if iid == 0:
            return ""
        return ws.names.get(iid)

    def _collect_entries(self, f, ws, groups, responses) -> None:
        from sentinel_tpu.models import constants as C

        cols = f.columns
        seqs = cols["seq"]
        ts = cols["ts"]
        acq = cols["acquire"]
        etype = cols["entry_type"]
        rid = cols["resource_id"]
        cid = cols["context_id"]
        oid = cols["origin_id"]
        aoff = cols["args_off"]
        alen = cols["args_len"]
        out = responses.setdefault(f.worker_id, [])
        now_ms = self._engine.clock.now_ms()
        for i in range(f.n):
            res = self._name(ws, int(rid[i]))
            ctx = self._name(ws, int(cid[i]))
            org = self._name(ws, int(oid[i]))
            if res is None or ctx is None or org is None:
                # Undecodable id (a skipped frame lost the intern): a
                # distinct fast shed, never a guess at a resource.
                out.append((int(seqs[i]), 0, E.BLOCK_SHED, 0, 0))
                self.counters["decode_drops"] += 1
                continue
            t = int(ts[i])
            if t < 0:
                t = now_ms
            args = ()
            if alen[i]:
                try:
                    args = fr.decode_args(
                        f.varbytes[int(aoff[i]) : int(aoff[i]) + int(alen[i])]
                    )
                except (ValueError, IndexError, fr.struct.error):
                    out.append((int(seqs[i]), 0, E.BLOCK_SHED, 0, 0))
                    self.counters["decode_drops"] += 1
                    continue
            et = int(etype[i])
            if et not in (0, 1):  # EntryType.IN / EntryType.OUT
                # Malformed wire value: the same per-row fast shed as
                # an undecodable id — one bad row must never abort the
                # rest of the drained batch.
                out.append((int(seqs[i]), 0, E.BLOCK_SHED, 0, 0))
                self.counters["decode_drops"] += 1
                continue
            trace = f.traces[i * 26 : (i + 1) * 26]
            key = (res, ctx or C.CONTEXT_DEFAULT_NAME, org,
                   C.EntryType(et))
            groups.setdefault(key, []).append(
                (f.worker_id, int(seqs[i]), t, int(acq[i]), args, trace)
            )

    def _collect_exits(self, f, ws, exits) -> None:
        cols = f.columns
        now_ms = self._engine.clock.now_ms()
        for i in range(f.n):
            res = self._name(ws, int(cols["resource_id"][i]))
            ctx = self._name(ws, int(cols["context_id"][i]))
            org = self._name(ws, int(cols["origin_id"][i]))
            if res is None or ctx is None or org is None:
                self.counters["decode_drops"] += 1
                continue
            et = int(cols["entry_type"][i])
            if et not in (0, 1):
                self.counters["decode_drops"] += 1
                continue
            t = int(cols["ts"][i])
            exits.append(
                (
                    f.worker_id, res, ctx, org, et,
                    now_ms if t < 0 else t,
                    int(cols["rt"][i]), int(cols["count"][i]),
                    int(cols["err"][i]), int(cols["spec"][i]),
                )
            )

    # -- exits ----------------------------------------------------------
    def _apply_exits(self, exits: List[tuple]) -> None:
        """Grouped columnar exits: one submit_exit_bulk per
        (rows, resource, speculative) — completions NEVER shed, and the
        per-worker live ledger gates which completions apply at all.

        Pairing comes FIRST: an exit that finds no live ledger
        admission is dropped (counted in ``exits_unpaired``), because
        each of its causes means the engine-side gauge was never (or no
        longer) charged — a policy-served caller whose entry never
        reached the engine (transient engine-dead read at the client),
        a dead-worker reap that already auto-exited the admission, or a
        post-reset completion from the dead world. Applying any of
        those would double-release and drive THREAD gauges negative;
        the reap remains the backstop for the complementary case
        (admission without a completion)."""
        if not exits:
            return
        from sentinel_tpu.models import constants as C

        eng = self._engine
        by_key: Dict[tuple, list] = {}
        # One engine-lock resolve per distinct identity, not per row —
        # exits repeat identities heavily by construction, and the
        # engine lock is every submitting thread's critical section.
        # Rows resolve OUTSIDE the plane lock (_rows_for nests the
        # engine lock), then one plane-lock pass pairs the whole batch.
        rows_memo: Dict[tuple, object] = {}
        resolved: List[tuple] = []
        for (wid, res, ctx, org, et, ts, rt, count, err, spec) in exits:
            ident = (res, ctx or C.CONTEXT_DEFAULT_NAME, org, int(et))
            if ident in rows_memo:
                rows = rows_memo[ident]
            else:
                rows = rows_memo[ident] = self._rows_for(
                    ident[0], ident[1], ident[2], C.EntryType(ident[3])
                )
            if rows is None:
                continue  # pass-through admissions charge no gauge
            # spec: unknown(0)/speculative(1) release mirror
            resolved.append((wid, rows, res, spec != 2, ts, rt, count, err))
        unpaired = 0
        with self._lock:
            for (wid, rows, res, spec_b, ts, rt, count, err) in resolved:
                live = self._workers[wid].live
                # The exit's spec flag may disagree with the admit-time
                # ledger key (a worker's default speculative=None reads
                # as mirror-release True while a spec-off admit was
                # recorded False) — try the exact key, then the flipped
                # flag, and RELEASE with the admit-time flag (the
                # mirror was charged, or not, at admit).
                paired = False
                for k in (
                    (rows, res, spec_b, count),
                    (rows, res, not spec_b, count),
                ):
                    cur = live.get(k, 0)
                    if cur > 0:
                        if cur > 1:
                            live[k] = cur - 1
                        else:
                            live.pop(k, None)
                        spec_b = k[2]
                        paired = True
                        break
                if not paired:
                    # Partial-count completion: Entry.exit(count) may
                    # release fewer (or more) than the admit acquired —
                    # in-process parity applies the EXIT's count. Pair
                    # with any live admission of the same (rows,
                    # resource), preferring the exit's spec flag, and
                    # forget that admission so the reap cannot
                    # re-release it; the acquire/count difference stays
                    # charged, exactly like the in-process gauge.
                    cand = None
                    for k in live:
                        if k[0] == rows and k[1] == res:
                            cand = k
                            if k[2] == spec_b:
                                break
                    if cand is not None:
                        cur = live[cand]
                        if cur > 1:
                            live[cand] = cur - 1
                        else:
                            live.pop(cand, None)
                        spec_b = cand[2]
                        paired = True
                if not paired:
                    unpaired += 1
                    continue
                by_key.setdefault((rows, res, spec_b), []).append(
                    (wid, ts, rt, count, err)
                )
        if unpaired:
            self.counters["exits_unpaired"] += unpaired
        for (rows, res, spec_b), items in by_key.items():
            n = len(items)
            eng.submit_exit_bulk(
                rows, n,
                ts=np.fromiter((i[1] for i in items), np.int64, n),
                rt=np.fromiter((i[2] for i in items), np.int64, n),
                count=np.fromiter((i[3] for i in items), np.int64, n),
                err=np.fromiter((i[4] for i in items), np.int64, n),
                resource=res,
                speculative=spec_b,
            )
            self.counters["exits"] += n

    def _apply_reasserts(self, f, ws: _WorkerState) -> None:
        """Worker reconnect after an engine hot-restart: rebuild this
        worker's live-admission ledger from its re-assertion and charge
        what the NEW world never saw admitted — +1 device THREAD gauge
        per live admission (the restore installs gauges at zero; see
        failover.restore_durable) and the persistent mirror's live
        counter for mirror-charged admits. The worker replays its
        buffered dead-window completions BEHIND this frame on the same
        FIFO ring, so they pair against exactly these ledger lines."""
        from sentinel_tpu.models import constants as C

        eng = self._engine
        if f.flags & fr.F_FRAME_RECONNECT:
            self.counters["worker_reconnects"] += 1
            if eng.telemetry.enabled:
                eng.telemetry.note_ipc_reconnect()
        cols = f.columns
        charged = 0
        for i in range(f.n):
            res = self._name(ws, int(cols["resource_id"][i]))
            ctx = self._name(ws, int(cols["context_id"][i]))
            org = self._name(ws, int(cols["origin_id"][i]))
            et = int(cols["entry_type"][i])
            cnt = int(cols["count"][i])
            acq = int(cols["acquire"][i])
            if res is None or ctx is None or org is None or cnt <= 0:
                self.counters["decode_drops"] += 1
                continue
            if et not in (0, 1):
                self.counters["decode_drops"] += 1
                continue
            rows = self._rows_for(
                res, ctx or C.CONTEXT_DEFAULT_NAME, org, C.EntryType(et)
            )
            if rows is None:
                continue  # pass-through admissions charge no gauge
            spec_b = int(cols["spec"][i]) == 1
            with self._lock:
                k = (rows, res, spec_b, acq)
                live = ws.live
                live[k] = live.get(k, 0) + cnt
            eng._submit_gauge_comp(rows, cnt)
            if spec_b and eng.speculative.enabled:
                eng.failover.fallback.assert_live(res, cnt)
            charged += cnt
        if charged:
            self.counters["reasserts"] += charged
            eng.flush()

    def _rows_for(self, res, ctx, org, etype):
        eng = self._engine
        with eng._lock:
            return eng.resolve_entry_rows(res, ctx, org, etype)

    # -- admissions -----------------------------------------------------
    def _decide_groups(self, groups: Dict[tuple, list], responses) -> None:
        """The batch window's dispatch shape, frame-fed: one columnar
        submit_bulk per (resource, ctx, origin, entry_type) group with
        per-request ts/acquire columns; rule classes bulk declines fall
        back to per-request submit_entry on the same flush."""
        eng = self._engine
        with self._lock:
            world = self._world
        settled: List[tuple] = []
        all_spec = True
        for (res, ctx, org, etype), reqs in groups.items():
            n = len(reqs)
            ts_col = np.fromiter((r[2] for r in reqs), np.int32, n)
            acq_col = np.fromiter((r[3] for r in reqs), np.int32, n)
            args_col = None
            if any(r[4] for r in reqs):
                args_col = [r[4] for r in reqs]
            try:
                op = eng.submit_bulk(
                    res, n, ts=ts_col, acquire=acq_col, context_name=ctx,
                    origin=org, entry_type=etype, args_column=args_col,
                )
                is_bulk = True
                if op is not None:
                    # Per-request trace identity (the group-level tag
                    # would record bounded group rows at fill).
                    op.trace = None
                    spec = op.spec_admitted is not None
                else:
                    spec = True  # pass-through: nothing to settle
            except ValueError:
                op = [
                    eng.submit_entry(
                        res, ctx, org, int(acq_col[i]), etype,
                        ts=int(ts_col[i]), args=reqs[i][4],
                    )
                    for i in range(n)
                ]
                is_bulk = False
                spec = False
            settled.append(((res, ctx, org, etype), reqs, op, is_bulk))
            all_spec = all_spec and spec
        if all_spec and eng.speculative.enabled:
            eng._spec_maybe_settle()
        elif eng.has_pending():
            eng.flush()
        for key, reqs, op, is_bulk in settled:
            if is_bulk:
                self._fan_out_bulk(key, reqs, op, responses, world)
            else:
                self._fan_out_entries(key, reqs, op, responses, world)

    def _fan_out_bulk(self, key, reqs, op, responses, world) -> None:
        res, ctx, org, _etype = key
        if op is None:
            for (wid, seq, _ts, _acq, _args, trace) in reqs:
                responses.setdefault(wid, []).append(
                    (seq, 1, E.PASS, 0, 0)
                )
                self._record_trace(trace, res, org, ctx, True, E.PASS, -1, "")
            return
        # A never-enqueued group (valve shed, cold-ceiling block) was
        # already trace-recorded by the engine's own record_bulk at
        # submit — the plane must not record the same rows again.
        recorded_at_submit = op.src is None
        flush_seq = -1
        pend = op._pending
        if pend is not None:
            flush_seq = pend._seq
        spec = op.spec_admitted is not None
        adm = op.admitted  # materializes a pending fetch if needed
        adm_l = adm.tolist()
        rsn_l = op.reason.tolist()
        wait_l = op.wait_ms.tolist()
        degraded = bool(op.spec_degraded) if spec else False
        fl = (fr.F_SPECULATIVE if spec else 0) | (
            fr.F_DEGRADED if degraded else 0
        )
        rows = op.rows
        with self._lock:
            ledger_live = self._world == world
            for i, (wid, seq, _ts, acq, _args, trace) in enumerate(reqs):
                responses.setdefault(wid, []).append(
                    (seq, 1 if adm_l[i] else 0, rsn_l[i], wait_l[i], fl)
                )
                if adm_l[i] and ledger_live:
                    live = self._workers[wid].live
                    k = (rows, res, spec or degraded, acq)
                    live[k] = live.get(k, 0) + 1
        if recorded_at_submit:
            return
        prov = "speculative" if spec else ""
        for i, (_wid, _seq, _ts, _acq, _args, trace) in enumerate(reqs):
            self._record_trace(
                trace, res, org, ctx, bool(adm_l[i]), int(rsn_l[i]),
                flush_seq, prov, degraded=degraded,
            )

    def _fan_out_entries(self, key, reqs, ops, responses, world) -> None:
        res, ctx, org, _etype = key
        verdicts = [op.verdict if op is not None else None for op in ops]
        with self._lock:
            ledger_live = self._world == world
            for (wid, seq, _ts, acq, _args, _trace), op, v in zip(
                reqs, ops, verdicts
            ):
                if op is None:
                    responses.setdefault(wid, []).append(
                        (seq, 1, E.PASS, 0, 0)
                    )
                    continue
                fl = (fr.F_SPECULATIVE if v.speculative else 0) | (
                    fr.F_DEGRADED if v.degraded else 0
                )
                responses.setdefault(wid, []).append(
                    (seq, 1 if v.admitted else 0, v.reason, v.wait_ms, fl)
                )
                if v.admitted and ledger_live:
                    live = self._workers[wid].live
                    k = (op.rows, res, v.speculative or v.degraded, acq)
                    live[k] = live.get(k, 0) + 1
        # Singles carry the engine's own trace records (submit_entry
        # stamped op.trace on the plane thread) — same stance as the
        # batch window's fallback path.

    def _record_trace(
        self, trace: bytes, res, org, ctx, admitted, reason, flush_seq,
        provenance, degraded: bool = False,
    ) -> None:
        tracer = self._engine.admission_trace
        if not tracer.enabled:
            return
        from sentinel_tpu.metrics.admission_trace import TraceContext, TraceTag

        unpacked = fr.unpack_trace(trace)
        if unpacked is not None:
            tid, sid, sampled = unpacked
            tag = TraceTag(
                TraceContext(tid, sid, sampled), sampled, time.perf_counter()
            )
        else:
            tag = tracer.make_tag()
        tracer.record_admission(
            tag, res, org, ctx, admitted, reason, flush_seq,
            time.perf_counter(), degraded=degraded, provenance=provenance,
        )

    # -- responses ------------------------------------------------------
    def _send_responses(self, responses: Dict[int, list]) -> None:
        for wid, rows in responses.items():
            if not rows:
                continue
            ring = self.responses[wid]
            if ring is None and self.prefix:
                # Named mode: the worker attached through a PREVIOUS
                # plane's channel, but the ring name is deterministic —
                # re-attach and keep answering (the hot-restart case).
                try:
                    with self._lock:
                        ring = self._ensure_response_locked(wid)
                except (OSError, ValueError):
                    ring = None
            if ring is None:
                # Frames from a worker slot that never took a channel
                # from THIS plane object (stale attach): nowhere to
                # answer — the callers' waits fall to the policy path.
                self.counters["responses_dropped"] += len(rows)
                continue
            n = len(rows)
            seqs = np.fromiter((r[0] for r in rows), np.uint64, n)
            adm = np.fromiter((r[1] for r in rows), np.uint8, n)
            rsn = np.fromiter((r[2] for r in rows), np.int16, n)
            wms = np.fromiter((r[3] for r in rows), np.int32, n)
            fl = np.fromiter((r[4] for r in rows), np.uint8, n)
            cap = max(1, (self.slot_bytes - 64) // 16)
            for lo in range(0, n, cap):
                hi = min(n, lo + cap)
                payload = fr.encode_verdicts(
                    wid, seqs[lo:hi], adm[lo:hi], rsn[lo:hi], wms[lo:hi],
                    fl[lo:hi],
                )
                deadline = time.monotonic() + 0.25
                while not ring.try_push(payload):
                    if time.monotonic() > deadline:
                        self.counters["responses_dropped"] += hi - lo
                        break
                    time.sleep(0.0002)

    # -- control-plane duties -------------------------------------------
    def _publish_control(self, force: bool = False) -> None:
        from sentinel_tpu.runtime.failover import HEALTHY, parse_policy

        eng = self._engine
        health = HEALTH_HEALTHY
        fo = eng.failover
        if fo.armed and fo.state != HEALTHY:
            health = HEALTH_DEGRADED
        if self._handoff:
            health = HEALTH_HANDOFF
        if self.closed:
            health = HEALTH_CLOSED
        self.control.beat_engine(health)
        if self._spans.enabled:
            # The engine IS the ruler source: its skew to the header
            # beat is ~0, but noting it keeps the journal meta uniform
            # across roles.
            _e, _h, _g, wall = self.control.engine_view()
            self._spans.note_ruler(wall)
        raw = config.get(config.FAILOVER_POLICY) or "open"
        if force or raw != self._policy_published:
            default, overrides = parse_policy(raw)
            self.control.publish_policy(default, overrides)
            self._policy_published = raw

    def _fold_sheds(self, wid: int, cumulative: int) -> None:
        ws = self._workers[wid]
        delta = (cumulative - ws.shed_seen) & 0xFFFFFFFF
        if 0 < delta < (1 << 31):
            ws.shed_seen = cumulative
            self.counters["worker_sheds"] += delta
            eng = self._engine
            eng.ingest.note_ipc_shed(delta)
            if eng.telemetry.enabled:
                eng.telemetry.note_ipc_shed(delta)

    def _check_workers(self, now: float) -> None:
        """Heartbeat sweep: a worker whose epoch stopped advancing for
        ``worker.dead.ms`` is dead — auto-exit its live admissions so
        the device AND mirror THREAD gauges return to exactly 0."""
        for wid in range(self.workers_max):
            ws = self._workers[wid]
            try:
                epoch, _wall, pid, shed = self.control.worker_view(wid)
            except (ValueError, TypeError):
                continue
            if pid == 0 and not ws.attached:
                continue
            if pid != 0:
                self._fold_sheds(wid, shed)
            if epoch != ws.last_epoch:
                ws.last_epoch = epoch
                ws.last_seen = now
                if pid != 0:
                    ws.attached = True
                    self._claimed.discard(wid)
                continue
            if not ws.attached:
                continue
            if (now - ws.last_seen) * 1e3 >= self.worker_dead_ms:
                self._reap_worker(wid, ws)

    def _reap_worker(self, wid: int, ws: _WorkerState) -> None:
        with self._lock:
            live, ws.live = ws.live, {}
            ws.attached = False
            self._claimed.discard(wid)
            ws.last_epoch = 0
            # The control slot is about to zero: a replacement worker
            # on this id restarts its cumulative shed count from 0, so
            # the fold baseline must follow or its first sheds read as
            # a giant (ignored) wraparound delta.
            ws.shed_seen = 0
        self.control.clear_worker(wid)
        self.counters["worker_deaths"] += 1
        eng = self._engine
        n_released = 0
        for (rows, res, spec_b, acq), n in live.items():
            if n <= 0:
                continue
            # Chunked to max_batch: submit_exit_bulk refuses oversized
            # groups, and an aborted release loop would leak every
            # remaining key's gauge charge forever (the ledger was
            # already swapped out).
            for lo in range(0, n, eng.max_batch):
                eng.submit_exit_bulk(
                    rows, min(eng.max_batch, n - lo), rt=0, count=acq,
                    err=0, resource=res, speculative=spec_b,
                )
            n_released += n
        if n_released:
            self.counters["auto_exits"] += n_released
            eng.flush()
        if eng.telemetry.enabled:
            eng.telemetry.note_ipc_worker_death(n_released)

    def on_engine_reset(self) -> None:
        """Engine.reset() hook: the engine just rebuilt its node rows
        and zeroed every gauge, so the per-worker live-admission
        ledgers reference a dead world — releasing them later would
        drive fresh gauges negative. Drop the ledgers (the reset
        already zeroed what they tracked) and bump the intern
        generation so workers re-intern against the fresh plane state."""
        with self._lock:
            self._world += 1
            for ws in self._workers:
                ws.live = {}
                ws.names = {}
        self.control.bump_intern_gen()

    # ------------------------------------------------------------------
    # readers / lifecycle
    # ------------------------------------------------------------------
    def live_workers(self) -> int:
        return sum(1 for ws in self._workers if ws.attached)

    def snapshot(self) -> dict:
        with self._lock:
            counters = dict(self.counters)
            live = [
                {
                    "worker_id": wid,
                    "attached": ws.attached,
                    "live_admissions": sum(ws.live.values()),
                    "interned": len(ws.names),
                }
                for wid, ws in enumerate(self._workers)
                if ws.attached or ws.names
            ]
        return {
            "enabled": True,
            "closed": self.closed,
            "workers_max": self.workers_max,
            "live_workers": self.live_workers(),
            "ring_slots": self.request.slots,
            "slot_bytes": self.slot_bytes,
            "ring_occupancy": round(self.request.occupancy(), 4),
            "wakeup": "adaptive" if self.adaptive_wakeup else "sleep",
            "intern_gen": self.control.intern_gen(),
            "engine_epoch": self.engine_epoch,
            "shm_prefix": self.prefix,
            "reattached": self.attached,
            "handoff": self._handoff,
            "counters": counters,
            "workers": live,
        }

    def handoff(self, wait_ms: Optional[int] = None) -> dict:
        """Planned live handoff, old-world side: publish HANDOFF on
        the control header (workers HOLD new admissions for the
        successor instead of serving policy verdicts), keep draining
        until the request ring stays empty for a couple of heartbeats
        (in-flight admissions and completions settle against THIS
        engine), then detach abandon-style — no CLOSED word, no worker
        reap, no unlink — leaving the rings, the worker ledgers and the
        HANDOFF word in place for the successor's attach (boot-epoch
        bump -> normal reconnect/reassert). Returns drain stats."""
        if self.closed:
            return {"drained": False, "drain_ms": 0.0}
        if wait_ms is None:
            wait_ms = config.get_int(config.IPC_HANDOFF_WAIT_MS, 3000)
        self._handoff = True
        try:
            self._publish_control(force=True)
        except (ValueError, TypeError):
            pass
        # Sustained-empty: one observation of an empty ring can race a
        # worker mid-push; require it to STAY empty for two heartbeat
        # periods (covers the worker window flusher and any frame the
        # drainer is currently deciding — the thread join below waits
        # out the final _drain_once).
        quiet_s = 2.0 * self.heartbeat_ms / 1e3
        deadline = time.monotonic() + max(1, int(wait_ms)) / 1e3
        t0 = time.monotonic()
        quiet_since: Optional[float] = None
        drained = False
        while time.monotonic() < deadline:
            if self.request.occupancy() > 0.0:
                quiet_since = None
            elif quiet_since is None:
                quiet_since = time.monotonic()
            elif time.monotonic() - quiet_since >= quiet_s:
                drained = True
                break
            time.sleep(0.001)
        drain_ms = (time.monotonic() - t0) * 1e3
        self.closed = True
        self._stop.set()
        for t in (self._thread, self._ctrl):
            if t is not None:
                t.join(5.0)
        self._thread = None
        self._ctrl = None
        # Straggler sweep: a worker that read a pre-HANDOFF health word
        # and then got descheduled can land a frame between the quiet
        # window's last occupancy read and the drainer join above — it
        # would otherwise sit in the ring as dead-world backlog (gen-
        # gated by the successor) with its caller parked to the policy
        # timeout. Answer it from THIS world before detaching.
        try:
            while self.request.occupancy() > 0.0:
                if not self._drain_once():
                    break
        except (ValueError, OSError):
            pass
        if self._engine.ipc_plane is self:
            self._engine.ipc_plane = None
        if self._spans.enabled:
            try:
                self._spans.spill()
            except OSError:
                pass
        self.request.close()
        for r in self.responses:
            if r is not None:
                r.close()
        self.control.close()
        return {"drained": drained, "drain_ms": round(drain_ms, 3)}

    def abandon(self) -> None:
        """Chaos/test hook: die like ``kill -9`` would — stop the
        threads and drop the shm mappings WITHOUT publishing CLOSED,
        reaping workers, or unlinking the segments. Workers observe a
        stale heartbeat (policy-served verdicts), the segments persist,
        and a new plane on the same prefix re-attaches warm. Never part
        of a graceful path — ``close()`` is."""
        if self.closed:
            return
        self.closed = True
        self._stop.set()
        for t in (self._thread, self._ctrl):
            if t is not None:
                t.join(5.0)
        self._thread = None
        self._ctrl = None
        if self._engine.ipc_plane is self:
            self._engine.ipc_plane = None
        self.request.close()
        for r in self.responses:
            if r is not None:
                r.close()
        self.control.close()

    def close(self, join_timeout_s: float = 5.0) -> None:
        """Stop serving: publish CLOSED (workers fail over to the
        policy snapshot), drain what is already in the ring, stop the
        drainer, release every worker's live admissions, and unlink the
        segments."""
        if self.closed:
            return
        self.closed = True
        try:
            self.control.set_health(HEALTH_CLOSED)
            self.control.beat_engine(HEALTH_CLOSED)
        except (ValueError, TypeError):
            pass
        self._stop.set()
        for t in (self._thread, self._ctrl):
            if t is not None:
                t.join(join_timeout_s)
                if t.is_alive():
                    self._engine.closed_dirty = True
        self._thread = None
        self._ctrl = None
        # Final sweep: live admissions from still-attached workers are
        # released like a death — the engine is leaving, its gauges
        # must not stay charged by callers it can no longer hear.
        for wid, ws in enumerate(self._workers):
            if ws.attached and ws.live:
                self._reap_worker(wid, ws)
        if self._engine.ipc_plane is self:
            self._engine.ipc_plane = None
        if self._spans.enabled:
            try:
                self._spans.spill()
            except OSError:
                pass
        self.request.destroy()
        for r in self.responses:
            if r is not None:
                r.destroy()
        self.control.destroy()
