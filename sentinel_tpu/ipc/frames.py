"""Pickle-free columnar frame codec for the multi-process ingest plane.

One frame = one ring slot's payload: a fixed header, an intern block
(strings crossing the boundary for the first time on this connection),
fixed numpy columns, and a varbytes region (args values). Strings that
repeat — resource, context, origin — ride as 32-bit **intern ids**
scoped to the (worker, intern generation) connection: each crosses the
boundary exactly once; the engine keeps the per-worker id→name decode
table, and a generation bump in the control header (plane restart)
makes every worker re-intern from scratch.

The PR-4 W3C trace identity survives the process boundary as a packed
26-byte column per row (16-byte trace id, 8-byte span id, flags,
presence) — the engine-side plane reconstructs the
:class:`~sentinel_tpu.metrics.admission_trace.TraceContext` and records
per-request admission traces exactly like the batch window does.

Frame kinds::

    ENTRY    n single admissions (mixed resources; the plane regroups
             onto the columnar spine) — columns ts/acquire/entry_type/
             resource/context/origin ids + trace + per-row args
    EXIT     n completions — never shed, never blocked; released even
             while the engine is DEGRADED
    BULK     one pre-grouped columnar group (one resource) of n rows —
             the worker-side analog of submit_bulk
    VERDICT  n (req_id, admitted, reason, wait_ms, flags) rows fanned
             back on a worker's response ring

Everything is little-endian and fixed-width; encode is a handful of
``tobytes`` joins, decode a handful of ``np.frombuffer`` views — no
pickle, no per-row Python on the hot columns.
"""

from __future__ import annotations

import struct
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

KIND_ENTRY = 1
KIND_EXIT = 2
KIND_BULK = 3
KIND_VERDICT = 4
# Worker reconnect (PR 15): after an engine hot-restart (control
# header's boot epoch bumped) a worker re-asserts its live-admission
# ledger so the NEW engine can rebuild per-worker ledgers and charge
# the THREAD gauges its world never saw admitted.
KIND_REASSERT = 5

# Frame-header flag bits.
F_FRAME_RECONNECT = 1  # first frame of one reconnect re-assertion

# Frame header: kind u8, flags u8, worker u16, n u32, base_seq u64,
# intern_gen u32, shed u32, n_interns u32, varbytes u32 -> 28 bytes.
_HDR = struct.Struct("<BBHIQIIII")
_INTERN_HDR = struct.Struct("<II")  # id, byte length

_TRACE_BYTES = 26  # 16B trace id + 8B span id + 1B flags + 1B present


class IpcVerdict(NamedTuple):
    """A worker-visible verdict — the wire twin of the engine's
    :class:`~sentinel_tpu.runtime.engine.Verdict` (no rule bean: rule
    objects do not cross the process boundary; ``limit_type`` carries
    the shed cause / system dimension string)."""

    admitted: bool
    reason: int
    wait_ms: int
    limit_type: str = ""
    degraded: bool = False
    speculative: bool = False


# verdict flag bits
F_SPECULATIVE = 1
F_DEGRADED = 2


def pack_trace(
    trace_id: str, span_id: str, sampled: bool
) -> bytes:
    """One row's packed traceparent (the worker encodes the AMBIENT
    inbound context — parent span, not a child: the admission record is
    a child of the inbound hop, and the engine mints its span id at
    record time exactly like the in-process tracer)."""
    try:
        t = bytes.fromhex(trace_id)
        s = bytes.fromhex(span_id)
    except ValueError:
        return b"\x00" * _TRACE_BYTES
    if len(t) != 16 or len(s) != 8:
        return b"\x00" * _TRACE_BYTES
    return t + s + bytes([1 if sampled else 0, 1])


def unpack_trace(raw: bytes) -> Optional[Tuple[str, str, bool]]:
    """(trace_id, span_id, sampled) or None when the row was untraced."""
    if len(raw) != _TRACE_BYTES or raw[25] == 0:
        return None
    return raw[:16].hex(), raw[16:24].hex(), bool(raw[24] & 1)


EMPTY_TRACE = b"\x00" * _TRACE_BYTES


# ---------------------------------------------------------------------------
# args value codec (tag + fixed/length-prefixed payload per value)
# ---------------------------------------------------------------------------
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")
_U32 = struct.Struct("<I")
_U16 = struct.Struct("<H")
_I64_MIN, _I64_MAX = -(1 << 63), (1 << 63) - 1


def _enc_value(v, out: List[bytes]) -> None:
    if v is None:
        out.append(b"N")
    elif v is True:
        out.append(b"T")
    elif v is False:
        out.append(b"F")
    elif type(v) is int and _I64_MIN <= v <= _I64_MAX:
        out.append(b"i")
        out.append(_I64.pack(v))
    elif isinstance(v, float):
        out.append(b"f")
        out.append(_F64.pack(v))
    elif isinstance(v, str):
        b = v.encode("utf-8", "surrogatepass")
        out.append(b"s")
        out.append(_U32.pack(len(b)))
        out.append(b)
    elif isinstance(v, bytes):
        out.append(b"b")
        out.append(_U32.pack(len(v)))
        out.append(v)
    elif isinstance(v, (list, tuple, set, frozenset)):
        items = list(v)
        out.append(b"(")
        out.append(_U16.pack(len(items)))
        for it in items:
            _enc_value(it, out)
    else:
        # Arbitrary objects cannot cross pickle-free; their stable
        # string key is what param rules match on anyway.
        b = repr(v).encode("utf-8", "surrogatepass")
        out.append(b"s")
        out.append(_U32.pack(len(b)))
        out.append(b)


def encode_args(args: Sequence[object]) -> bytes:
    if not args:
        return b""
    out: List[bytes] = [_U16.pack(len(args))]
    for v in args:
        _enc_value(v, out)
    return b"".join(out)


def _args_need(buf: bytes, off: int, nbytes: int) -> None:
    # Truncated payloads must raise (never silently short-slice into a
    # misdecoded value): args blobs are now a DURABLE format (capture
    # segments), not just ring slots sliced to exact length.
    if off + nbytes > len(buf):
        raise ValueError(
            f"truncated args payload: need {nbytes} bytes at {off}, "
            f"have {len(buf) - off}"
        )


def _dec_value(buf: bytes, off: int) -> Tuple[object, int]:
    tag = buf[off : off + 1]
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"i":
        _args_need(buf, off, 8)
        return _I64.unpack_from(buf, off)[0], off + 8
    if tag == b"f":
        _args_need(buf, off, 8)
        return _F64.unpack_from(buf, off)[0], off + 8
    if tag in (b"s", b"b"):
        _args_need(buf, off, 4)
        n = _U32.unpack_from(buf, off)[0]
        off += 4
        _args_need(buf, off, n)
        raw = buf[off : off + n]
        return (
            raw.decode("utf-8", "surrogatepass") if tag == b"s" else raw
        ), off + n
    if tag == b"(":
        _args_need(buf, off, 2)
        n = _U16.unpack_from(buf, off)[0]
        off += 2
        items = []
        for _ in range(n):
            v, off = _dec_value(buf, off)
            items.append(v)
        return tuple(items), off
    raise ValueError(f"bad args tag {tag!r} at {off - 1}")


def decode_args(buf: bytes) -> Tuple[object, ...]:
    if not buf:
        return ()
    _args_need(buf, 0, 2)
    n = _U16.unpack_from(buf, 0)[0]
    off = 2
    out = []
    for _ in range(n):
        v, off = _dec_value(buf, off)
        out.append(v)
    return tuple(out)


# ---------------------------------------------------------------------------
# request rows (worker -> plane)
# ---------------------------------------------------------------------------
class EntryRow(NamedTuple):
    """One pending single admission on the worker side (ids already
    interned by the client)."""

    seq: int
    resource_id: int
    context_id: int
    origin_id: int
    entry_type: int
    acquire: int
    ts: int  # engine-relative ms, or -1 = plane stamps at decode
    trace: bytes  # packed 26B (EMPTY_TRACE when untraced)
    args: bytes  # encode_args payload ("" = no args)


class ExitRow(NamedTuple):
    seq: int
    resource_id: int
    context_id: int
    origin_id: int
    entry_type: int
    ts: int
    rt: int
    count: int
    err: int
    spec: int  # 0 unknown, 1 speculative, 2 device-decided


class ReassertRow(NamedTuple):
    """One live-admission ledger line re-asserted after an engine
    hot-restart: ``count`` admissions of ``acquire`` each, still live
    in this worker (their exits will arrive later and must pair)."""

    resource_id: int
    context_id: int
    origin_id: int
    entry_type: int
    spec: int  # 1 = mirror-charged (speculative/degraded admit)
    acquire: int
    count: int


def encode_entries(
    worker_id: int,
    rows: Sequence[EntryRow],
    interns: Sequence[Tuple[int, bytes]],
    intern_gen: int,
    shed_count: int,
    kind: int = KIND_ENTRY,
    group_meta: Optional[bytes] = None,
) -> bytes:
    """ENTRY/BULK frame bytes. ``group_meta`` (BULK only) rides at the
    head of the varbytes region (args offsets are relative to its
    end)."""
    n = len(rows)
    meta = group_meta or b""
    seqs = np.fromiter((r.seq for r in rows), np.uint64, n)
    ts = np.fromiter((r.ts for r in rows), np.int64, n)
    acq = np.fromiter((r.acquire for r in rows), np.int32, n)
    etype = np.fromiter((r.entry_type for r in rows), np.int8, n)
    rid = np.fromiter((r.resource_id for r in rows), np.int32, n)
    cid = np.fromiter((r.context_id for r in rows), np.int32, n)
    oid = np.fromiter((r.origin_id for r in rows), np.int32, n)
    traces = b"".join(r.trace for r in rows)
    args_off = np.empty(n, np.uint32)
    args_len = np.empty(n, np.uint32)
    var_parts: List[bytes] = [meta]
    pos = len(meta)
    for i, r in enumerate(rows):
        args_off[i] = pos
        args_len[i] = len(r.args)
        if r.args:
            var_parts.append(r.args)
            pos += len(r.args)
    varbytes = b"".join(var_parts)
    intern_parts: List[bytes] = []
    for iid, raw in interns:
        intern_parts.append(_INTERN_HDR.pack(iid, len(raw)))
        intern_parts.append(raw)
    intern_blob = b"".join(intern_parts)
    hdr = _HDR.pack(
        kind, 0, worker_id, n, int(rows[0].seq) if n else 0,
        intern_gen & 0xFFFFFFFF, shed_count & 0xFFFFFFFF,
        len(interns), len(varbytes),
    )
    return b"".join(
        (
            hdr, intern_blob,
            seqs.tobytes(), ts.tobytes(), acq.tobytes(), etype.tobytes(),
            rid.tobytes(), cid.tobytes(), oid.tobytes(), traces,
            args_off.tobytes(), args_len.tobytes(), varbytes,
        )
    )


def encode_entries_columns(
    worker_id: int,
    base_seq: int,
    ts,
    acquire,
    entry_type: int,
    resource_id: int,
    context_id: int,
    origin_id: int,
    interns: Sequence[Tuple[int, bytes]],
    intern_gen: int,
    kind: int = KIND_BULK,
) -> bytes:
    """Vectorized ENTRY/BULK frame for a uniform columnar group: one
    (resource, context, origin, entry_type) shared by all rows,
    ``seq = base_seq + arange(n)``, per-row ``ts``/``acquire`` arrays,
    no traces, no args, no group meta. Byte-identical to
    ``encode_entries`` over the equivalent EntryRow list — the capture
    journal's bulk spill uses this because a Python row loop at bulk
    group sizes would cost more than the admission work it records."""
    ts = np.ascontiguousarray(ts, np.int64)
    acq = np.ascontiguousarray(acquire, np.int32)
    n = len(ts)
    seqs = np.arange(base_seq, base_seq + n, dtype=np.uint64)
    zeros_u32 = np.zeros(n, np.uint32).tobytes()
    intern_parts: List[bytes] = []
    for iid, raw in interns:
        intern_parts.append(_INTERN_HDR.pack(iid, len(raw)))
        intern_parts.append(raw)
    hdr = _HDR.pack(
        kind, 0, worker_id, n, base_seq if n else 0,
        intern_gen & 0xFFFFFFFF, 0, len(interns), 0,
    )
    return b"".join(
        (
            hdr, b"".join(intern_parts),
            seqs.tobytes(), ts.tobytes(), acq.tobytes(),
            np.full(n, entry_type, np.int8).tobytes(),
            np.full(n, resource_id, np.int32).tobytes(),
            np.full(n, context_id, np.int32).tobytes(),
            np.full(n, origin_id, np.int32).tobytes(),
            EMPTY_TRACE * n,
            zeros_u32, zeros_u32,
        )
    )


def encode_exits(
    worker_id: int,
    rows: Sequence[ExitRow],
    interns: Sequence[Tuple[int, bytes]],
    intern_gen: int,
    shed_count: int,
    extras: bytes = b"",
) -> bytes:
    """EXIT frame bytes. ``extras`` (optional) rides as the frame's
    varbytes region — the ring clients never set it; the capture
    journal uses it for the per-exit param-thread-row sidecar."""
    n = len(rows)
    seqs = np.fromiter((r.seq for r in rows), np.uint64, n)
    ts = np.fromiter((r.ts for r in rows), np.int64, n)
    rid = np.fromiter((r.resource_id for r in rows), np.int32, n)
    cid = np.fromiter((r.context_id for r in rows), np.int32, n)
    oid = np.fromiter((r.origin_id for r in rows), np.int32, n)
    etype = np.fromiter((r.entry_type for r in rows), np.int8, n)
    rt = np.fromiter((r.rt for r in rows), np.int32, n)
    count = np.fromiter((r.count for r in rows), np.int32, n)
    err = np.fromiter((r.err for r in rows), np.int32, n)
    spec = np.fromiter((r.spec for r in rows), np.int8, n)
    intern_parts: List[bytes] = []
    for iid, raw in interns:
        intern_parts.append(_INTERN_HDR.pack(iid, len(raw)))
        intern_parts.append(raw)
    hdr = _HDR.pack(
        KIND_EXIT, 0, worker_id, n, int(rows[0].seq) if n else 0,
        intern_gen & 0xFFFFFFFF, shed_count & 0xFFFFFFFF,
        len(interns), len(extras),
    )
    return b"".join(
        (
            hdr, b"".join(intern_parts),
            seqs.tobytes(), ts.tobytes(), rid.tobytes(), cid.tobytes(),
            oid.tobytes(), etype.tobytes(), rt.tobytes(), count.tobytes(),
            err.tobytes(), spec.tobytes(), extras,
        )
    )


def encode_reasserts(
    worker_id: int,
    rows: Sequence[ReassertRow],
    interns: Sequence[Tuple[int, bytes]],
    intern_gen: int,
    shed_count: int,
    head: bool = False,
) -> bytes:
    """REASSERT frame bytes; ``head`` marks the FIRST frame of one
    reconnect sequence (the plane counts reconnect events off it, not
    off every chunk)."""
    n = len(rows)
    rid = np.fromiter((r.resource_id for r in rows), np.int32, n)
    cid = np.fromiter((r.context_id for r in rows), np.int32, n)
    oid = np.fromiter((r.origin_id for r in rows), np.int32, n)
    etype = np.fromiter((r.entry_type for r in rows), np.int8, n)
    spec = np.fromiter((r.spec for r in rows), np.int8, n)
    acq = np.fromiter((r.acquire for r in rows), np.int32, n)
    count = np.fromiter((r.count for r in rows), np.int32, n)
    intern_parts: List[bytes] = []
    for iid, raw in interns:
        intern_parts.append(_INTERN_HDR.pack(iid, len(raw)))
        intern_parts.append(raw)
    hdr = _HDR.pack(
        KIND_REASSERT, F_FRAME_RECONNECT if head else 0, worker_id, n, 0,
        intern_gen & 0xFFFFFFFF, shed_count & 0xFFFFFFFF,
        len(interns), 0,
    )
    return b"".join(
        (
            hdr, b"".join(intern_parts),
            rid.tobytes(), cid.tobytes(), oid.tobytes(), etype.tobytes(),
            spec.tobytes(), acq.tobytes(), count.tobytes(),
        )
    )


class DecodedFrame(NamedTuple):
    kind: int
    worker_id: int
    n: int
    intern_gen: int
    shed_count: int
    interns: List[Tuple[int, bytes]]
    columns: Dict[str, np.ndarray]
    traces: bytes  # ENTRY/BULK: n * 26 bytes ("" otherwise)
    varbytes: bytes
    flags: int = 0


def _need(payload: bytes, off: int, nbytes: int, what: str) -> None:
    # Every region read is bounds-checked up front so a torn segment
    # tail (or a fuzzer's truncation) raises ONE clean ValueError
    # instead of struct.error / a silently short np.frombuffer slice
    # that would misalign every column after it.
    if off + nbytes > len(payload):
        raise ValueError(
            f"truncated frame: {what} needs {nbytes} bytes at {off}, "
            f"payload is {len(payload)}"
        )


def decode_frame(payload: bytes) -> DecodedFrame:
    _need(payload, 0, _HDR.size, "header")
    (
        kind, _flags, worker_id, n, _base, gen, shed, n_interns, var_len,
    ) = _HDR.unpack_from(payload, 0)
    off = _HDR.size
    interns: List[Tuple[int, bytes]] = []
    for _ in range(n_interns):
        _need(payload, off, _INTERN_HDR.size, "intern header")
        iid, ln = _INTERN_HDR.unpack_from(payload, off)
        off += _INTERN_HDR.size
        _need(payload, off, ln, "intern bytes")
        interns.append((iid, payload[off : off + ln]))
        off += ln

    def col(dtype, count=n):
        nonlocal off
        _need(payload, off, np.dtype(dtype).itemsize * count, "column")
        a = np.frombuffer(payload, dtype=dtype, count=count, offset=off)
        off += a.nbytes
        return a

    columns: Dict[str, np.ndarray] = {}
    traces = b""
    varbytes = b""
    if kind in (KIND_ENTRY, KIND_BULK):
        columns["seq"] = col(np.uint64)
        columns["ts"] = col(np.int64)
        columns["acquire"] = col(np.int32)
        columns["entry_type"] = col(np.int8)
        columns["resource_id"] = col(np.int32)
        columns["context_id"] = col(np.int32)
        columns["origin_id"] = col(np.int32)
        _need(payload, off, n * _TRACE_BYTES, "trace column")
        traces = payload[off : off + n * _TRACE_BYTES]
        off += n * _TRACE_BYTES
        columns["args_off"] = col(np.uint32)
        columns["args_len"] = col(np.uint32)
        _need(payload, off, var_len, "varbytes")
        varbytes = payload[off : off + var_len]
    elif kind == KIND_EXIT:
        columns["seq"] = col(np.uint64)
        columns["ts"] = col(np.int64)
        columns["resource_id"] = col(np.int32)
        columns["context_id"] = col(np.int32)
        columns["origin_id"] = col(np.int32)
        columns["entry_type"] = col(np.int8)
        columns["rt"] = col(np.int32)
        columns["count"] = col(np.int32)
        columns["err"] = col(np.int32)
        columns["spec"] = col(np.int8)
        _need(payload, off, var_len, "varbytes")
        varbytes = payload[off : off + var_len]
    elif kind == KIND_VERDICT:
        columns["seq"] = col(np.uint64)
        columns["admitted"] = col(np.uint8)
        columns["reason"] = col(np.int16)
        columns["wait_ms"] = col(np.int32)
        columns["flags"] = col(np.uint8)
    elif kind == KIND_REASSERT:
        columns["resource_id"] = col(np.int32)
        columns["context_id"] = col(np.int32)
        columns["origin_id"] = col(np.int32)
        columns["entry_type"] = col(np.int8)
        columns["spec"] = col(np.int8)
        columns["acquire"] = col(np.int32)
        columns["count"] = col(np.int32)
    else:
        raise ValueError(f"unknown frame kind {kind}")
    return DecodedFrame(
        kind, worker_id, n, gen, shed, interns, columns, traces, varbytes,
        _flags,
    )


def encode_verdicts(
    worker_id: int,
    seqs: np.ndarray,
    admitted: np.ndarray,
    reason: np.ndarray,
    wait_ms: np.ndarray,
    flags: np.ndarray,
) -> bytes:
    n = len(seqs)
    hdr = _HDR.pack(
        KIND_VERDICT, 0, worker_id, n, int(seqs[0]) if n else 0, 0, 0, 0, 0
    )
    return b"".join(
        (
            hdr,
            np.ascontiguousarray(seqs, np.uint64).tobytes(),
            np.ascontiguousarray(admitted, np.uint8).tobytes(),
            np.ascontiguousarray(reason, np.int16).tobytes(),
            np.ascontiguousarray(wait_ms, np.int32).tobytes(),
            np.ascontiguousarray(flags, np.uint8).tobytes(),
        )
    )


# Per-row fixed column bytes of an ENTRY/BULK frame:
# seq 8 + ts 8 + acquire 4 + entry_type 1 + resource 4 + context 4 +
# origin 4 + trace 26 + args_off 4 + args_len 4.
ENTRY_ROW_BYTES = 67
# Per-row bytes of an EXIT frame: seq 8 + ts 8 + resource 4 +
# context 4 + origin 4 + entry_type 1 + rt 4 + count 4 + err 4 + spec 1.
EXIT_ROW_BYTES = 42
# Per-row bytes of a REASSERT frame: resource 4 + context 4 + origin 4
# + entry_type 1 + spec 1 + acquire 4 + count 4.
REASSERT_ROW_BYTES = 22
# Header + intern-blob reserve per frame (a fresh connection's intern
# records ride the same slot).
FRAME_RESERVE = 512


def entry_frame_cap(slot_bytes: int, avg_args: int = 0) -> int:
    """Conservative rows-per-frame bound for a slot size. With args the
    caller must budget BYTES, not rows — see the client's greedy
    packing (a frame larger than the slot is refused by the ring and
    would otherwise read as phantom backpressure)."""
    per_row = ENTRY_ROW_BYTES + max(0, avg_args)
    return max(1, (slot_bytes - FRAME_RESERVE) // per_row)
