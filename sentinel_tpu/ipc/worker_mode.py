"""Worker mode: run a whole process's ``api.entry`` surface through its
:class:`~sentinel_tpu.ipc.worker.IngestClient`.

PR 13 gave worker processes the raw ``entry``/``exit``/``bulk`` client;
this module closes the last mile of the multi-worker scale-out story:
with ``sentinel.tpu.ipc.worker.mode`` on and a client attached, the
public API — ``api.entry``, ``api.try_entry``, ``api.entry_async``,
``api.entry_windowed(_async)`` — and therefore **all six adapters**
route through the client instead of a local engine. The process never
constructs an :class:`Engine` (no device memory, no flush threads); it
is pure encode + wait against the plane's shared-memory rings, and the
client's micro-window (``sentinel.tpu.ipc.client.window.*``) is the
worker-side coalescing tier the adapter batch window plays in-process.

Deployment is one line either way:

* ``api.run_workers(target, n=4)`` — ensure the plane on the global
  engine, spawn ``n`` worker processes (descendants, so the claim lock
  and doorbells travel), each calling ``target(worker_id, *args)`` in
  worker mode; returns a :class:`WorkerSet`.
* ``python tools/ipc_launch.py module:app --workers 4`` — the CLI
  wrapper serving a WSGI app.

Verdict surface parity: blocked admissions raise the same
:class:`BlockError` subclasses (``errors.error_for_verdict`` from the
wire reason code), admitted ones return a :class:`WorkerEntry` with the
``Entry`` contract the adapters rely on (``exit()`` / ``set_error()`` /
context-manager / ``verdict`` provenance — ``speculative``/``degraded``
ride the verdict flags across the boundary). Rule beans do not cross
the process boundary, so ``verdict.blocked_rule`` is always None here.
"""

from __future__ import annotations

import threading
import time
from typing import Optional, Sequence

from sentinel_tpu.core import errors as E
from sentinel_tpu.utils.config import config

_lock = threading.Lock()
_client = None  # this process's ROUTED IngestClient (worker mode on)
_attached = None  # last client attach() created, routed or not


def attach(channel, worker_id: int, route: Optional[bool] = None,
           heartbeat: bool = True):
    """Attach this process to a plane channel. ``route`` None reads
    ``sentinel.tpu.ipc.worker.mode``; True installs the api hook so the
    whole entry surface rides the client. A previously attached client
    — routed or not — is detached (and closed) first: two clients on
    one response ring would race its tail pointer and strand half the
    verdicts."""
    from sentinel_tpu.ipc.worker import IngestClient

    global _client, _attached
    detach(close=True)
    cli = IngestClient(channel, worker_id, heartbeat=heartbeat)
    if route is None:
        route = config.get_bool(config.IPC_WORKER_MODE, False)
    with _lock:
        _attached = cli
        _client = cli if route else None
    if route:
        from sentinel_tpu.core import api

        api.set_worker_client(cli)
    return cli


def detach(close: bool = True) -> None:
    """Uninstall the api hook and (by default) close the client —
    including a non-routed one, which would otherwise keep its reader
    and heartbeat threads alive with no handle to stop them."""
    global _client, _attached
    with _lock:
        cli, _client, _attached = _attached, None, None
    from sentinel_tpu.core import api

    api.set_worker_client(None)
    if cli is not None and close:
        try:
            cli.close()
        except Exception:
            # The caller may have closed a non-routed client directly.
            pass


def current():
    """This process's routed client, or None (worker mode off)."""
    return _client


class WorkerEntry:
    """The worker-mode twin of :class:`api.Entry`: same public surface
    (``exit()``, ``set_error()``, ``verdict``, context manager, ambient
    context-stack bookkeeping), completion delivered through the
    client's exit path instead of ``engine.submit_exit``. RT is wall
    time measured here — the worker has no engine clock; the plane
    stamps its own ts at decode."""

    __slots__ = (
        "resource", "context_name", "origin", "entry_type", "acquire",
        "verdict", "context", "error", "pass_through",
        "_cli", "_create_pc", "_exited",
    )

    def __init__(self, cli, resource, context_name, origin, entry_type,
                 acquire, verdict, context) -> None:
        self.resource = resource
        self.context_name = context_name
        self.origin = origin
        self.entry_type = int(entry_type)
        self.acquire = acquire
        self.verdict = verdict  # frames.IpcVerdict (wire provenance)
        self.context = context
        self.error: Optional[BaseException] = None
        self.pass_through = False
        self._cli = cli
        self._create_pc = time.monotonic()
        self._exited = False

    def set_error(self, e: BaseException) -> None:
        from sentinel_tpu.core import api

        try:
            traceable = api.should_trace(e)
        except Exception:
            from sentinel_tpu.utils.record_log import record_log

            record_log.error(
                "[Tracer] exception predicate/filter raised — not tracing",
                exc_info=True,
            )
            traceable = False
        if traceable and self.error is None:
            self.error = e

    def exit(self, count: Optional[int] = None) -> None:
        if self._exited:
            return
        self._exited = True
        from sentinel_tpu.core.context import ContextUtil

        rt = int((time.monotonic() - self._create_pc) * 1000)
        n = count if count is not None else self.acquire
        err = 0
        if self.error is not None and not isinstance(self.error, E.BlockError):
            err = n
        v = self.verdict
        # The mirror-release gate: speculative/degraded admits charged
        # the engine-side host mirror — the exit's spec flag must say
        # so (the plane's ledger pairing relies on it too).
        self._cli.exit(
            self.resource, self.context_name, self.origin, self.entry_type,
            rt=rt, count=n, err=err,
            speculative=bool(v.speculative or v.degraded),
        )
        ctx = self.context
        if ctx is not None and ctx.entry_stack and ctx.entry_stack[-1] is self:
            ctx.entry_stack.pop()
            if not ctx.entry_stack and ctx.auto:
                ContextUtil.exit()

    def __enter__(self) -> "WorkerEntry":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.set_error(exc)
        self.exit()
        return False


def client_entry(
    cli,
    resource: str,
    entry_type,
    count: int,
    origin: Optional[str],
    args: Sequence[object],
    with_context: bool,
    prio: bool = False,
) -> WorkerEntry:
    """The worker-mode body of ``api.entry``/``entry_async``/
    ``entry_windowed``: same context bookkeeping as ``_do_entry``, the
    admission decided by the plane through this process's client (the
    client's micro-window coalesces concurrent calls when armed).
    Raises the mapped BlockError on a declined verdict.

    Prioritized (occupy/borrow) entries are refused loudly: the wire
    format carries no prio bit and the plane's columnar spine declines
    prio ops anyway — silently downgrading them to normal admission
    would change verdicts (a borrow-admit would read as a block)."""
    if prio:
        raise ValueError(
            "prio entries are not supported in ipc worker mode — the "
            "frame format carries no occupy semantics; serve "
            "prioritized resources from the engine process"
        )
    from sentinel_tpu.core.context import ContextUtil
    from sentinel_tpu.models import constants as C

    ctx = ContextUtil.get_context()
    if ctx is None:
        # detached_enter, NOT true_enter: the latter resolves the
        # entrance row via get_engine(), lazily constructing a full
        # Engine (device memory, flush threads — and, with ipc.enabled
        # replayed, a second IngestPlane) inside the worker on the
        # first request.
        ctx = ContextUtil.detached_enter(C.CONTEXT_DEFAULT_NAME, origin or "")
    eff_origin = origin if origin is not None else ctx.origin
    context_name = ctx.name if not ctx.is_null else C.CONTEXT_DEFAULT_NAME
    v = cli.entry(
        resource,
        context_name=context_name,
        origin=eff_origin,
        acquire=count,
        entry_type=int(entry_type),
        args=tuple(args),
    )
    if not v.admitted:
        if ctx.auto and not ctx.entry_stack:
            ContextUtil.exit()
        raise E.error_for_verdict(
            v.reason, resource, limit_type=v.limit_type
        )
    if v.wait_ms > 0:
        time.sleep(v.wait_ms / 1e3)
    e = WorkerEntry(
        cli, resource, context_name, eff_origin, entry_type, count, v,
        ctx if with_context else None,
    )
    if with_context:
        ctx.entry_stack.append(e)
    elif ctx.auto and not ctx.entry_stack:
        ContextUtil.exit()
    return e


def worker_main(channel, worker_id: int, overrides, target, args) -> object:
    """Spawn bootstrap (top-level so ``multiprocessing`` spawn children
    import it by name): replay the parent's runtime config, arm worker
    mode, attach, run ``target(worker_id, *args)``, detach."""
    for k, v in (overrides or {}).items():
        config.set(k, v)
    config.set(config.IPC_WORKER_MODE, "true")
    # A worker is never an engine host: the parent's replayed runtime
    # config may carry ipc.enabled=true (how IT armed the plane), and
    # any stray get_engine() here would then build a SECOND IngestPlane
    # — new shm rings, drainer threads, per-worker device memory.
    config.set(config.IPC_ENABLED, "false")
    attach(channel, worker_id)
    try:
        return target(worker_id, *args)
    finally:
        detach()


class WorkerSet:
    """Handle on a spawned worker fleet (``api.run_workers``)."""

    def __init__(self, procs, plane) -> None:
        self.procs = list(procs)
        self.plane = plane

    def __iter__(self):
        return iter(self.procs)

    def __len__(self) -> int:
        return len(self.procs)

    def alive(self) -> int:
        return sum(1 for p in self.procs if p.is_alive())

    def join(self, timeout: Optional[float] = None) -> None:
        for p in self.procs:
            p.join(timeout)

    def stop(self, timeout: float = 5.0) -> None:
        """Terminate the workers (their live admissions auto-release
        through the plane's dead-worker sweep / final close sweep)."""
        for p in self.procs:
            if p.is_alive():
                p.terminate()
        for p in self.procs:
            p.join(timeout)
