"""IngestClient: the worker-process side of the multi-process plane.

A worker process speaks the same entry/exit/bulk surface the adapters
speak, but every decision is made by the ONE engine process: requests
encode into columnar frames on the shared-memory MPSC request ring,
verdicts come back on this worker's SPSC response ring. The client
holds no device state and takes no engine locks — it is pure encode +
wait, safe to call from many threads of a GIL-bound server process.

Failure stances (the worker half of the plane's failure matrix):

* **ring full** → a local ``BLOCK_SHED`` verdict with cause
  ``ipc_ring`` — never a stall. The shed count is published through
  the control header so the engine's IngestValve accounting sees it
  (backpressure stays observable fleet-side even though the decision
  was made here).
* **engine dead** (health word CLOSED, heartbeat stale past
  ``sentinel.tpu.ipc.engine.dead.ms``, or a verdict wait past
  ``...timeout.ms``) → verdicts come from the per-resource
  fail-open/closed failover policy snapshot the plane published into
  the control header, marked ``degraded`` — the same stance the
  in-process engine takes when the DEVICE dies (runtime/failover.py).
* **exits are never shed and never policy-served**: a completion is
  how gauges drain, so the client retries a full ring briefly and only
  drops a completion once the engine is provably gone (a dead engine
  has no gauges left to leak).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from struct import error as struct_error
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from sentinel_tpu.core import errors as E
from sentinel_tpu.ipc import frames as fr
from sentinel_tpu.ipc.ring import (
    HEALTH_CLOSED,
    HEALTH_HANDOFF,
    ControlBlock,
    ShmRing,
    _wall_ms,
    resolve_spin_us,
)
from sentinel_tpu.metrics.spans import get_journal
from sentinel_tpu.metrics.spans import wall_ms as _span_wall_ms
from sentinel_tpu.utils.config import config


@dataclass
class PlaneChannel:
    """Everything a worker needs to attach: shared-memory segment names
    + geometry + the producer claim lock. Picklable through
    ``multiprocessing`` process spawning (the lock travels via mp's own
    reduction, so workers must be descendants of the plane's
    process)."""

    control_name: str
    request_name: str
    response_name: str  # THIS worker slot's SPSC response ring
    ring_slots: int
    slot_bytes: int
    resp_slots: int
    workers_max: int
    request_lock: object = field(repr=False, default=None)
    # Adaptive-wakeup doorbells (multiprocessing.Semaphore, travel like
    # the claim lock): None in "sleep" wakeup mode.
    request_doorbell: object = field(repr=False, default=None)
    response_doorbell: object = field(repr=False, default=None)


class _Waiter:
    __slots__ = ("event", "verdicts", "need")

    def __init__(self, need: int) -> None:
        self.event = threading.Event()
        self.verdicts: Dict[int, tuple] = {}
        self.need = need


def _byte_chunks(sizes: Sequence[int], budget: int, what: str) -> List[Tuple[int, int]]:
    """Greedy byte-budget chunking shared by ``bulk()`` and the
    micro-window flusher: ``[lo, hi)`` windows whose encoded rows fit
    one slot's frame budget. A single row over the budget is a
    config/caller mismatch, not backpressure — ValueError, never a
    shed."""
    chunks: List[Tuple[int, int]] = []
    lo = 0
    size = 0
    for j, rb in enumerate(sizes):
        if rb > budget:
            raise ValueError(
                f"{what}: row {j}'s encoded args ({rb}B) exceed the "
                f"frame budget ({budget}B) — raise "
                "sentinel.tpu.ipc.slot.bytes or shrink the args"
            )
        if size + rb > budget and j > lo:
            chunks.append((lo, j))
            lo = j
            size = 0
        size += rb
    if sizes:
        chunks.append((lo, len(sizes)))
    return chunks


class IngestClient:
    """One worker's connection to the plane (one per process; its
    methods are thread-safe)."""

    def __init__(
        self,
        channel: PlaneChannel,
        worker_id: int,
        heartbeat: bool = True,
    ) -> None:
        if not (0 <= worker_id < channel.workers_max):
            raise ValueError(
                f"worker_id {worker_id} out of range 0..{channel.workers_max - 1}"
            )
        self.worker_id = int(worker_id)
        self.channel = channel
        self.control = ControlBlock(
            channel.control_name, channel.workers_max
        )
        self.request = ShmRing(
            channel.request_name, channel.ring_slots, channel.slot_bytes,
            lock=channel.request_lock, doorbell=channel.request_doorbell,
        )
        self.response = ShmRing(
            channel.response_name, channel.resp_slots, channel.slot_bytes,
            doorbell=channel.response_doorbell,
        )
        self.heartbeat_ms = max(1, config.get_int(config.IPC_HEARTBEAT_MS, 100))
        self.engine_dead_ms = max(
            1, config.get_int(config.IPC_ENGINE_DEAD_MS, 1000)
        )
        self.timeout_ms = max(1, config.get_int(config.IPC_TIMEOUT_MS, 5000))
        # Death confirmation (sentinel.tpu.ipc.engine.dead.confirm.ms):
        # with confirm > 0 a stale wall clock alone does not declare the
        # engine dead — the worker opens a SUSPICION episode, rings the
        # request doorbell once (wakes a parked drainer) and probes the
        # published engine pid; a provably-alive engine gets up to
        # dead.ms + confirm.ms before the declaration lands, so
        # sub-second dead.ms on a pegged box has a measured
        # false-positive story instead of a flappy one. 0 (default) is
        # the PR-15 wall-staleness predicate exactly.
        self.dead_confirm_ms = max(
            0, config.get_int(config.IPC_ENGINE_DEAD_CONFIRM_MS, 0)
        )
        self.handoff_wait_ms = max(
            0, config.get_int(config.IPC_HANDOFF_WAIT_MS, 3000)
        )
        # Episode state has its OWN lock: engine_alive() runs under the
        # client lock on the window-flush path, so it must never take it.
        self._suspect_lock = threading.Lock()
        self._suspect_epoch = -1
        self._suspect_declared = False
        self._in_handoff = False
        # Adaptive wakeup (sentinel.tpu.ipc.wakeup=adaptive): the
        # reader spins briefly then parks on the response-ring doorbell
        # instead of the fixed 200 µs sleep-poll. Only meaningful when
        # the plane shipped a doorbell in the channel.
        wake = (config.get(config.IPC_WAKEUP) or "sleep").strip().lower()
        self.adaptive_wakeup = (
            wake == "adaptive" and channel.response_doorbell is not None
        )
        self._spin_s = resolve_spin_us(
            config.get_int(config.IPC_WAKEUP_SPIN_US, -1)
        ) / 1e6
        self._park_s = max(
            1, config.get_int(config.IPC_WAKEUP_PARK_MS, 5)
        ) / 1e3
        self._lock = threading.Lock()
        self._seq = 0
        # Per-connection intern table: each string crosses the boundary
        # exactly once per intern generation. _fresh buffers the
        # (id, bytes) records the NEXT frame must carry.
        self._intern: Dict[str, int] = {}
        self._fresh: List[Tuple[int, bytes]] = []
        self._intern_gen = self.control.intern_gen()
        self._next_id = 1
        self._waiters: Dict[int, _Waiter] = {}
        # Fleet span journal (metrics/spans.py): admission spans on
        # the control header's wall-ms ruler. Disabled (default) is
        # one bool read per call site.
        self._spans = get_journal("worker")
        self._shed_total = 0
        self.counters: Dict[str, int] = {
            "entries": 0, "bulk_rows": 0, "exits": 0, "exits_dropped": 0,
            "sheds": 0, "policy_served": 0, "frames": 0,
            "window_flushes": 0, "reconnects": 0, "exits_buffered": 0,
            "dead_suspicions": 0, "dead_false_alarms": 0,
            "dead_declared": 0, "handoff_holds": 0,
        }
        # Engine hot-restart reconnect (sentinel.tpu.ipc.reconnect.*):
        # the client keeps its OWN live-admission ledger — one line per
        # (identity, mirror-charged?, acquire) still running — so that
        # when the control header's boot epoch bumps (a NEW engine
        # attached to the same rings) it can re-assert exactly what is
        # live into the new world, and completions that could not be
        # delivered during the dead window buffer (bounded) for replay
        # instead of dropping. Off = PR-14 exactly: no ledger writes,
        # dead-window completions drop, a returning engine starts cold.
        self.reconnect_enabled = config.get_bool(config.IPC_RECONNECT, True)
        self.reconnect_exits_max = max(
            0, config.get_int(config.IPC_RECONNECT_EXITS_MAX, 4096)
        )
        # (resource, context, origin, entry_type, spec_b, acquire) ->
        # live admitted count (engine-decided admits only — policy
        # verdicts never reached the engine and must not re-assert).
        # ``_live_new`` holds admits decided by a NEW engine boot before
        # our reconnect completed: the new plane ledgered those at
        # fan-out, so re-asserting them would double-charge the gauges —
        # they merge into ``_live`` once the reassert lands.
        self._live: Dict[tuple, int] = {}
        self._live_new: Dict[tuple, int] = {}
        self._dead_exits: List[tuple] = []
        self._boot = self.control.engine_boot()
        self._reassert_boot: Optional[int] = None
        self._reassert_rows: List[tuple] = []
        self._reassert_head = True
        self._stop = threading.Event()
        # Micro-window (sentinel.tpu.ipc.client.window.{ms,max}):
        # concurrent entry/bulk/exit calls coalesce into one columnar
        # frame per bounded window — the client-side twin of
        # runtime/window.py's BatchWindow. Off (window.ms=0, the
        # default) keeps PR-13 per-call framing exactly: no flusher
        # thread, no buffered state, the armed check is one bool read.
        self.window_ms = max(
            0.0, config.get_float(config.IPC_CLIENT_WINDOW_MS, 0.0)
        )
        self.window_max = max(
            1, config.get_int(config.IPC_CLIENT_WINDOW_MAX, 256)
        )
        self.window_armed = self.window_ms > 0.0
        self._win_cond = threading.Condition(self._lock)
        self._win_rows: List[fr.EntryRow] = []
        # Buffered completions as IDENTITY tuples, not encoded rows:
        # exits retry across failed pushes, and a retried payload must
        # re-intern (a failed push rolls its fresh interns back — see
        # exit()'s per-call loop, which rebuilds for the same reason).
        self._win_exits: List[tuple] = []
        # Seqs of windowed rows that came through bulk(): the flusher
        # counts pushed rows into entries vs bulk_rows at flush time,
        # and the waiter may already be gone (caller timeout) by then.
        self._win_bulk: set = set()
        self._win_deadline: Optional[float] = None
        self._win_exit_stall: Optional[float] = None
        self._win_thread: Optional[threading.Thread] = None
        if self.window_armed:
            self._win_thread = threading.Thread(
                target=self._win_loop, name=f"ipc-window-{worker_id}",
                daemon=True,
            )
            self._win_thread.start()
        self._reader = threading.Thread(
            target=self._read_loop, name=f"ipc-reader-{worker_id}", daemon=True
        )
        self._reader.start()
        self._beat: Optional[threading.Thread] = None
        if heartbeat:
            self._beat = threading.Thread(
                target=self._beat_loop, name=f"ipc-beat-{worker_id}",
                daemon=True,
            )
            self._beat.start()
        self.control.beat_worker(self.worker_id, os.getpid())

    # ------------------------------------------------------------------
    # interning
    # ------------------------------------------------------------------
    def _intern_rollback_locked(self, interns: List[Tuple[int, bytes]]) -> None:
        """A frame carrying fresh intern records failed to push: FORGET
        those strings instead of re-queuing the records. A re-queued
        backlog grows without bound under sustained shed (and can push
        every future frame past the slot size — a permanent 100%-shed
        wedge); forgetting just means the string re-interns under a
        NEW id on its next use, which costs one duplicate crossing and
        nothing else (ids are monotonic, never reused for a different
        string, so the plane-side table stays consistent)."""
        for _iid, raw in interns:
            self._intern.pop(raw.decode("utf-8", "surrogatepass"), None)

    def _push_locked(self, encode) -> bool:
        """Encode + push one frame under the client lock. ``encode``
        is called with the intern-record list to carry. When the
        combined payload would exceed the slot (long fresh names past
        the FRAME_RESERVE budget), the interns ship FIRST as a
        zero-row preamble frame — otherwise an over-slot payload would
        read as permanent phantom ring backpressure. A push failure
        rolls the fresh interns back (see _intern_rollback_locked);
        intern records alone exceeding a slot raise ValueError (a
        config/caller mismatch, never backpressure)."""
        interns, self._fresh = self._fresh, []
        try:
            payload = encode(interns)
        except BaseException:
            # An encode failure (e.g. a count/rt outside int32) must
            # leave the intern table consistent: these records were
            # detached from _fresh but never shipped — forget them or
            # every later frame referencing the ids decode-drops.
            self._intern_rollback_locked(interns)
            raise
        if len(payload) > self.channel.slot_bytes and interns:
            pre = fr.encode_entries(
                self.worker_id, [], interns, self._intern_gen,
                self._shed_total,
            )
            if len(pre) > self.channel.slot_bytes:
                self._intern_rollback_locked(interns)
                raise ValueError(
                    "intern records exceed the frame budget — raise "
                    "sentinel.tpu.ipc.slot.bytes or shorten the names"
                )
            if not self.request.try_push(pre):
                self._intern_rollback_locked(interns)
                return False
            self.counters["frames"] += 1
            interns = []
            payload = encode([])
        if self.request.try_push(payload):
            self.counters["frames"] += 1
            return True
        self._intern_rollback_locked(interns)
        return False

    def _intern_locked(self, s: str) -> int:
        gen = self.control.intern_gen()
        if gen != self._intern_gen:
            # Plane restarted / table invalidated: every string crosses
            # again under the new generation.
            self._intern.clear()
            self._fresh = []
            self._intern_gen = gen
        i = self._intern.get(s)
        if i is None:
            i = self._next_id
            self._next_id += 1
            self._intern[s] = i
            self._fresh.append((i, s.encode("utf-8", "surrogatepass")))
        return i

    # ------------------------------------------------------------------
    # live-admission ledger + reconnect (engine hot-restart)
    # ------------------------------------------------------------------
    def _live_note_locked(self, key: tuple) -> None:
        # An admit decided by a newer engine boot than the one we have
        # re-asserted to is ALREADY in the new plane's ledger — keep it
        # out of the next reassert snapshot (merged after reconnect).
        if self.control.engine_boot() != self._boot:
            self._live_new[key] = self._live_new.get(key, 0) + 1
        else:
            self._live[key] = self._live.get(key, 0) + 1

    @staticmethod
    def _dec(d: Dict[tuple, int], k: tuple) -> bool:
        cur = d.get(k, 0)
        if cur <= 0:
            return False
        if cur > 1:
            d[k] = cur - 1
        else:
            d.pop(k, None)
        return True

    def _live_forget_locked(
        self, res, ctx, org, et, spec, count
    ) -> None:
        """Pair one completion with its ledger line: exact key first,
        flipped mirror flag next, then any line with the same identity
        — the client-side twin of the plane's exit pairing (a raw
        ``speculative=None`` exit reads spec 0 = unknown). The
        new-world ledger is tried first (most recent admits complete
        first under typical request lifetimes)."""
        spec_opts = (
            (True, False) if spec == 0
            else ((spec == 1), not (spec == 1))
        )
        for d in (self._live_new, self._live):
            for sb in spec_opts:
                if self._dec(d, (res, ctx, org, et, sb, count)):
                    return
        for d in (self._live_new, self._live):
            for k in list(d):
                if k[0] == res and k[1] == ctx and k[2] == org and k[3] == et:
                    self._dec(d, k)
                    return

    def _forget_exit_tuple_locked(self, t: tuple) -> None:
        res, ctx, org, et, _ts, _rt, count, _err, spec = t
        self._live_forget_locked(res, ctx, org, et, spec, count)

    def _buffer_dead_exits_locked(self, items: List[tuple]) -> None:
        """Completions that could not reach a DEAD engine buffer for
        replay after a hot-restart (their ledger lines stay live so the
        re-assertion still covers them and the replayed exits pair).
        Bounded: overflow drops oldest, counted — the dead-worker reap
        remains the gauge backstop for anything dropped."""
        self._dead_exits.extend(items)
        self.counters["exits_buffered"] += len(items)
        over = len(self._dead_exits) - self.reconnect_exits_max
        if over > 0:
            dropped = self._dead_exits[:over]
            del self._dead_exits[:over]
            self.counters["exits_dropped"] += len(dropped)
            for t in dropped:
                self._forget_exit_tuple_locked(t)

    def _maybe_reconnect(self) -> None:
        """Beat-loop hook: an engine-boot epoch change with a live
        engine means a NEW engine process attached to our rings —
        re-intern happens organically (the new plane bumped the intern
        generation), so the reconnect work is (1) re-assert the live
        ledger, (2) replay the dead-window completion buffer. Chunks
        that fail to push retry on the next beat tick; a SECOND restart
        mid-reassert restarts the sequence from the current ledger."""
        boot = self.control.engine_boot()
        if boot == self._boot or not self.engine_alive():
            return
        if self._boot == 0:
            # First-ever observation (attached before the plane's boot
            # bump landed): nothing was admitted through an older world
            # — but admits decided BETWEEN the bump and this tick were
            # routed to _live_new (note-time boot mismatch); fold them
            # into the main ledger or a LATER restart's reassert
            # snapshot would miss them.
            with self._lock:
                self._boot = boot
                for k, v in self._live_new.items():
                    self._live[k] = self._live.get(k, 0) + v
                self._live_new.clear()
            return
        with self._lock:
            # Refresh the intern generation FIRST: a zero-row head
            # frame (idle worker) never calls _intern_locked, and a
            # frame carrying the dead world's generation would be
            # gen-gated as stale backlog by the new plane — the
            # reconnect would count client-side but never plane-side.
            gen = self.control.intern_gen()
            if gen != self._intern_gen:
                self._intern.clear()
                self._fresh = []
                self._intern_gen = gen
            if self._reassert_boot != boot:
                self._reassert_boot = boot
                self._reassert_rows = [
                    key + (cnt,) for key, cnt in self._live.items()
                ]
                self._reassert_head = True
            budget = self.channel.slot_bytes - fr.FRAME_RESERVE
            cap = max(1, budget // fr.REASSERT_ROW_BYTES)
            while True:
                chunk = self._reassert_rows[:cap]
                rows = [
                    fr.ReassertRow(
                        resource_id=self._intern_locked(res),
                        context_id=self._intern_locked(ctx),
                        origin_id=self._intern_locked(org),
                        entry_type=et,
                        spec=1 if spec_b else 0,
                        acquire=acq,
                        count=cnt,
                    )
                    for (res, ctx, org, et, spec_b, acq, cnt) in chunk
                ]
                try:
                    ok = self._push_locked(
                        lambda interns, rows=rows: fr.encode_reasserts(
                            self.worker_id, rows, interns,
                            self._intern_gen, self._shed_total,
                            head=self._reassert_head,
                        )
                    )
                except Exception:
                    from sentinel_tpu.utils.record_log import record_log

                    record_log.error(
                        "[ipc] reassert encode failed — dropping chunk",
                        exc_info=True,
                    )
                    del self._reassert_rows[: len(chunk)]
                    continue
                if not ok:
                    return  # ring full / engine gone again: next beat
                self._reassert_head = False
                del self._reassert_rows[: len(chunk)]
                if not self._reassert_rows:
                    break
            # Ledger re-asserted: adopt the new world, fold the admits
            # the new engine decided mid-reconnect back into the main
            # ledger (its plane already carries them), and queue the
            # buffered completions for replay BEHIND the reassert
            # (same MPSC ring = FIFO, so they pair at the plane).
            self._boot = boot
            self._reassert_boot = None
            for k, v in self._live_new.items():
                self._live[k] = self._live.get(k, 0) + v
            self._live_new.clear()
            self.counters["reconnects"] += 1
            replay, self._dead_exits = self._dead_exits, []
        if replay:
            if self.window_armed:
                with self._lock:
                    self._win_join_locked(exits=replay)
            else:
                for t in replay:
                    (res, ctx, org, et, ts, rt, count, err, spec) = t
                    self.exit(
                        res, ctx, org, et, rt=rt, count=count, err=err,
                        ts=None if ts < 0 else ts,
                        speculative=(
                            None if spec == 0 else (spec == 1)
                        ),
                    )

    # ------------------------------------------------------------------
    # engine liveness + policy fallback
    # ------------------------------------------------------------------
    def engine_alive(self) -> bool:
        epoch, health, _gen, wall = self.control.engine_view()
        if health == HEALTH_CLOSED:
            return False
        if wall == 0:
            return False  # plane never heartbeat — not serving
        if self._spans.enabled:
            # The header beat IS the shared ruler: remember the latest
            # one so each journal spill carries this process's skew.
            self._spans.note_ruler(wall)
        self._in_handoff = health == HEALTH_HANDOFF
        stale = _wall_ms() - wall
        if stale <= self.engine_dead_ms:
            if self._suspect_epoch != -1:
                self._close_suspicion()
            return True
        if self.dead_confirm_ms <= 0:
            return False
        return self._confirm_alive(epoch, stale)

    def _close_suspicion(self) -> None:
        """The heartbeat resumed while a suspicion episode was open:
        the confirmation step held a pegged-but-alive engine out of the
        policy path — count the would-have-been false positive."""
        with self._suspect_lock:
            if self._suspect_epoch == -1:
                return
            if not self._suspect_declared:
                self.counters["dead_false_alarms"] += 1
            self._suspect_epoch = -1
            self._suspect_declared = False

    def _confirm_alive(self, epoch: int, stale: float) -> bool:
        """Wall clock stale past ``dead.ms`` with confirmation armed:
        defer the death declaration while the engine is PROVABLY alive
        (published pid answers signal 0), up to ``dead.ms +
        dead.confirm.ms``. One doorbell nudge per episode wakes a
        parked drainer whose control thread is merely starved."""
        with self._suspect_lock:
            if self._suspect_epoch != epoch:
                # New episode (keyed on the heartbeat epoch the engine
                # stalled at — a beat-then-stall restarts the clock).
                self._suspect_epoch = epoch
                self._suspect_declared = False
                self.counters["dead_suspicions"] += 1
                self.request.nudge()
            if self._suspect_declared:
                return False
            if stale > self.engine_dead_ms + self.dead_confirm_ms:
                self._suspect_declared = True
                self.counters["dead_declared"] += 1
                return False
            pid = self.control.engine_pid()
            if pid and _pid_alive(pid):
                return True
            self._suspect_declared = True
            self.counters["dead_declared"] += 1
            return False

    def _handoff_hold(self) -> bool:
        """The control header published HANDOFF: the old engine is
        draining in-flight work for a successor that attaches to the
        SAME rings. Hold this NEW admission (bounded by
        ``handoff.wait.ms``) until the successor beats and our beat
        loop has adopted its boot epoch — a planned handoff then serves
        ZERO policy verdicts. The hold spans the old world's
        detach->successor-attach gap (HANDOFF word with a stale wall
        still means "wait", not "dead"). Returns True when the push may
        proceed against the new world, False when the hold expired."""
        self.counters["handoff_holds"] += 1
        deadline = time.monotonic() + self.handoff_wait_ms / 1e3
        while time.monotonic() < deadline:
            if self._stop.is_set():
                return False
            _epoch, health, _gen, wall = self.control.engine_view()
            if health == HEALTH_CLOSED or wall == 0:
                return False
            if health != HEALTH_HANDOFF:
                self._in_handoff = False
                # Successor up: wait for OUR reconnect (beat loop) to
                # adopt its boot — pushing before the intern-generation
                # refresh would be gen-gated as dead-world backlog.
                if self._boot in (0, self.control.engine_boot()):
                    return (_wall_ms() - wall) <= self.engine_dead_ms
            time.sleep(0.002)
        return False

    def _policy_verdict(self, resource: str) -> fr.IpcVerdict:
        default, overrides = self.control.read_policy()
        mode = overrides.get(resource, default)
        self.counters["policy_served"] += 1
        if mode == "closed":
            return fr.IpcVerdict(
                False, E.BLOCK_FAILOVER, 0, degraded=True
            )
        return fr.IpcVerdict(True, E.PASS, 0, degraded=True)

    def _shed_verdict(self, n: int = 1) -> fr.IpcVerdict:
        with self._lock:
            self._shed_total += n
            self.counters["sheds"] += n
            # Cumulative count in our control slot (the plane folds the
            # delta into the engine's valve accounting even when no
            # frame ever gets through). Under the lock: the slot write
            # is a read-modify-write, and two shedding threads must not
            # lose an update.
            try:
                self.control.note_worker_shed(self.worker_id, n)
            except (ValueError, TypeError):
                pass
        return fr.IpcVerdict(False, E.BLOCK_SHED, 0, limit_type="ipc_ring")

    # ------------------------------------------------------------------
    # micro-window (sentinel.tpu.ipc.client.window.*)
    # ------------------------------------------------------------------
    def _win_join_locked(self, rows=(), exits=()) -> None:
        """Join the assembling micro-window (caller holds the client
        lock). The flusher wakes at the window deadline or when the
        row count reaches ``window.max`` — one ring claim + publish
        then answers for the whole window."""
        self._win_rows.extend(rows)
        self._win_exits.extend(exits)
        if self._win_deadline is None:
            self._win_deadline = time.monotonic() + self.window_ms / 1e3
            self._win_cond.notify_all()
        elif len(self._win_rows) >= self.window_max:
            self._win_cond.notify_all()

    def _win_due_locked(self) -> bool:
        if not self._win_rows and not self._win_exits:
            return False
        if len(self._win_rows) >= self.window_max:
            return True
        d = self._win_deadline
        return d is not None and time.monotonic() >= d

    def _win_loop(self) -> None:
        while True:
            with self._win_cond:
                while not self._stop.is_set() and not self._win_due_locked():
                    if self._win_rows or self._win_exits:
                        left = (
                            (self._win_deadline or time.monotonic())
                            - time.monotonic()
                        )
                        self._win_cond.wait(left if left > 0 else 0.0005)
                    else:
                        self._win_cond.wait(0.05)
                rows, self._win_rows = self._win_rows, []
                self._win_deadline = None
                try:
                    self._win_flush_locked(rows)
                except BaseException:
                    # Last-resort guard (the per-chunk and per-exit
                    # guards inside make this unreachable on known
                    # paths): a dead flusher strands every future
                    # windowed caller and leaks gauges forever — shed
                    # whatever is still unanswered instead. A row whose
                    # frame DID push before the failure keeps no waiter
                    # after this shed; its late verdict is tolerated
                    # (the reader pops waiters with a None default).
                    from sentinel_tpu.utils.record_log import record_log

                    record_log.error(
                        "[ipc] micro-window flush failed — shedding "
                        "the window", exc_info=True,
                    )
                    try:
                        self._win_shed_locked(rows)
                        if self._win_exits:
                            self.counters["exits_dropped"] += len(
                                self._win_exits
                            )
                            if self.reconnect_enabled:
                                for t in self._win_exits:
                                    self._forget_exit_tuple_locked(t)
                            self._win_exits = []
                    except BaseException:
                        pass
                if (
                    self._stop.is_set()
                    and not self._win_rows
                    and not self._win_exits
                ):
                    return

    def _win_flush_locked(self, rows: List[fr.EntryRow]) -> None:
        """Encode + push one window: the entry rows in greedy
        byte-budget chunks (per-row over-budget was refused at the API
        edge, so every chunk fits a slot), then the buffered exits.
        Caller holds the client lock."""
        budget = self.channel.slot_bytes - fr.FRAME_RESERVE
        chunks = _byte_chunks(
            [fr.ENTRY_ROW_BYTES + len(r.args) for r in rows], budget,
            "window",
        )
        spj = self._spans
        t_flush = _span_wall_ms() if (spj.enabled and rows) else 0.0
        for ci, (clo, chi) in enumerate(chunks):
            sub = rows[clo:chi]
            try:
                ok = self._push_locked(
                    lambda interns, sub=sub: fr.encode_entries(
                        self.worker_id, sub, interns, self._intern_gen,
                        self._shed_total,
                    )
                )
            except Exception:
                # An encode failure must not kill the flusher thread —
                # that would strand every future windowed caller and
                # leak the engine-side gauges permanently (the worker
                # keeps heartbeating, so the dead-worker reap never
                # fires). Shed this chunk and the rest of the window
                # (the per-call twin of an unanswerable call).
                from sentinel_tpu.utils.record_log import record_log

                record_log.error(
                    "[ipc] micro-window encode failed — shedding the "
                    "window's remaining chunks", exc_info=True,
                )
                for (slo, shi) in chunks[ci:]:
                    self._win_shed_locked(rows[slo:shi])
                break
            if ok:
                self.counters["window_flushes"] += 1
                # Per-call parity for the amortization counters: an
                # entry/bulk row counts only once its frame actually
                # pushed (a shed window must not read as served
                # entries in frames-per-entry).
                for r in sub:
                    if r.seq in self._win_bulk:
                        self._win_bulk.discard(r.seq)
                        self.counters["bulk_rows"] += 1
                    else:
                        self.counters["entries"] += 1
                continue
            # Ring full: this chunk AND every later chunk of the window
            # shed (per-call parity — a failed push is a local
            # BLOCK_SHED, never a stall; later chunks may reference
            # intern ids the failed push just rolled back, so pushing
            # them anyway would decode-drop at the plane). A dead
            # engine instead leaves the waiters to their own policy
            # fallback in _await_one — but the bookkeeping set must
            # still forget the rows, or every engine-dead window with
            # bulk rows grows it forever.
            if self.engine_alive():
                for (slo, shi) in chunks[ci:]:
                    self._win_shed_locked(rows[slo:shi])
            else:
                for (slo, shi) in chunks[ci:]:
                    for r in rows[slo:shi]:
                        self._win_bulk.discard(r.seq)
            break
        if spj.enabled and rows:
            spj.record(
                "win.flush", "worker", t_flush,
                _span_wall_ms() - t_flush,
                wid=self.worker_id, rows=len(rows),
                seq_lo=rows[0].seq, seq_hi=rows[-1].seq,
            )
        self._win_drain_exits_locked()

    def _win_shed_locked(self, sub: List[fr.EntryRow]) -> None:
        """Fan a shed verdict out to a failed chunk's waiters (caller
        holds the client lock; the inline twin of _shed_verdict)."""
        n = len(sub)
        self._shed_total += n
        self.counters["sheds"] += n
        try:
            self.control.note_worker_shed(self.worker_id, n)
        except (ValueError, TypeError):
            pass
        hit: Dict[_Waiter, bool] = {}
        for r in sub:
            self._win_bulk.discard(r.seq)
            w = self._waiters.pop(r.seq, None)
            if w is None:
                continue
            w.verdicts[r.seq] = (0, E.BLOCK_SHED, 0, 0)
            hit[w] = True
        for w in hit:
            w.event.set()

    def _win_drain_exits_locked(self) -> None:
        """Buffered completions → KIND_EXIT frames. Exits never shed:
        a full ring re-buffers them for the next window tick, bounded
        by the stall clock — dropped (and counted) only once the
        engine is gone or the stall outlives ``timeout.ms``, exactly
        the per-call exit() stance."""
        cap = max(1, (self.channel.slot_bytes - fr.FRAME_RESERVE)
                  // fr.EXIT_ROW_BYTES)
        if (
            self._win_exits
            and self.reconnect_enabled
            and not self._stop.is_set()
            and not self.engine_alive()
        ):
            # Dead engine: frames pushed now would be dead-world backlog
            # the next plane drops — buffer the window's completions for
            # post-restart replay instead (see exit()).
            moved, self._win_exits = self._win_exits, []
            self._buffer_dead_exits_locked(moved)
            self._win_exit_stall = None
            return
        while self._win_exits:
            chunk = self._win_exits[: cap]
            # (Re)intern per attempt: a failed push rolled its fresh
            # interns back, so a retried payload must carry fresh
            # records (stale ids decode-drop at the plane).
            rows = []
            for (res, ctx, org, et, ts, rt, count, err, spec) in chunk:
                seq = self._seq
                self._seq += 1
                rows.append(fr.ExitRow(
                    seq=seq,
                    resource_id=self._intern_locked(res),
                    context_id=self._intern_locked(ctx),
                    origin_id=self._intern_locked(org),
                    entry_type=et, ts=ts, rt=rt, count=count, err=err,
                    spec=spec,
                ))
            try:
                ok = self._push_locked(
                    lambda interns, rows=rows: fr.encode_exits(
                        self.worker_id, rows, interns, self._intern_gen,
                        self._shed_total,
                    )
                )
            except Exception:
                # An unencodable completion (e.g. a count outside
                # int32) must not kill the flusher: drop the chunk,
                # counted, and keep draining the rest.
                from sentinel_tpu.utils.record_log import record_log

                record_log.error(
                    "[ipc] micro-window exit encode failed — dropping "
                    "the chunk", exc_info=True,
                )
                self.counters["exits_dropped"] += len(chunk)
                if self.reconnect_enabled:
                    for t in chunk:
                        self._forget_exit_tuple_locked(t)
                del self._win_exits[: len(chunk)]
                self._win_exit_stall = None
                continue
            if ok:
                if self.reconnect_enabled:
                    for t in chunk:
                        self._forget_exit_tuple_locked(t)
                del self._win_exits[: len(chunk)]
                self.counters["exits"] += len(chunk)
                self._win_exit_stall = None
                continue
            now = time.monotonic()
            if self._win_exit_stall is None:
                self._win_exit_stall = now
            dead = not self.engine_alive()
            if dead and self.reconnect_enabled and not self._stop.is_set():
                # Engine gone: buffer the window's completions for
                # replay after a hot-restart instead of dropping them
                # (their ledger lines stay live — see exit()).
                moved, self._win_exits = self._win_exits, []
                self._buffer_dead_exits_locked(moved)
                self._win_exit_stall = None
            elif (
                dead
                or (now - self._win_exit_stall) > self.timeout_ms / 1e3
                or self._stop.is_set()
            ):
                if self.reconnect_enabled:
                    for t in self._win_exits:
                        self._forget_exit_tuple_locked(t)
                self.counters["exits_dropped"] += len(self._win_exits)
                self._win_exits = []
                self._win_exit_stall = None
            elif self._win_deadline is None:
                # Schedule a retry tick even if no new joins arrive.
                self._win_deadline = now + max(self.window_ms, 1.0) / 1e3
            break

    # ------------------------------------------------------------------
    # the API surface
    # ------------------------------------------------------------------
    def entry(
        self,
        resource: str,
        context_name: str = "",
        origin: str = "",
        acquire: int = 1,
        entry_type: int = 1,  # models.constants.EntryType.OUT — the engine API default
        args: Sequence[object] = (),
        ts: Optional[int] = None,
        trace=None,
        timeout_ms: Optional[int] = None,
    ) -> fr.IpcVerdict:
        """One blocking admission through the plane. ``trace`` is an
        object with ``trace_id``/``span_id``/``sampled`` (e.g. a
        TraceContext); None reads the ambient contextvar so adapter
        code keeps working unchanged inside a worker."""
        _check_entry_type(entry_type)
        alive = self.engine_alive()
        if self._in_handoff:
            alive = self._handoff_hold()
        if not alive:
            return self._policy_verdict(resource)
        if trace is None:
            trace = _ambient_trace()
        packed = (
            fr.pack_trace(trace.trace_id, trace.span_id, trace.sampled)
            if trace is not None
            else fr.EMPTY_TRACE
        )
        spj = self._spans
        t_join = _span_wall_ms() if spj.enabled else 0.0
        args_blob = fr.encode_args(args)
        if (
            fr.ENTRY_ROW_BYTES + len(args_blob)
            > self.channel.slot_bytes - fr.FRAME_RESERVE
        ):
            # A row that can never fit a slot is a config/caller
            # mismatch, not backpressure — it must not read as a shed.
            raise ValueError(
                "entry: encoded args exceed the frame budget — raise "
                "sentinel.tpu.ipc.slot.bytes or shrink the args"
            )
        with self._lock:
            seq = self._seq
            self._seq += 1
            row = fr.EntryRow(
                seq=seq,
                resource_id=self._intern_locked(resource),
                context_id=self._intern_locked(context_name),
                origin_id=self._intern_locked(origin),
                entry_type=int(entry_type),
                acquire=int(acquire),
                ts=-1 if ts is None else int(ts),
                trace=packed,
                args=args_blob,
            )
            w = _Waiter(1)
            self._waiters[seq] = w
            if self.window_armed:
                # Micro-window: the flusher ships one frame for every
                # call that lands inside the window (shed/policy
                # outcomes fan back through the same waiter).
                self._win_join_locked(rows=[row])
                ok = True
            else:
                ok = self._push_locked(
                    lambda interns: fr.encode_entries(
                        self.worker_id, [row], interns, self._intern_gen,
                        self._shed_total,
                    )
                )
                if not ok:
                    del self._waiters[seq]
        if not ok:
            return self._shed_verdict()
        t_push = _span_wall_ms() if spj.enabled else 0.0
        if not self.window_armed:
            # Windowed entries count at flush time instead, once their
            # frame actually pushes — a later window shed must not
            # have pre-counted the row.
            self.counters["entries"] += 1
        out = self._await_one(
            w, seq, resource, timeout_ms,
            live_ident=(resource, context_name, origin, int(entry_type),
                        int(acquire)),
        )
        if spj.enabled:
            # One span per admission: t0 at join, `push_ms` when the
            # frame (or window join) was in the ring, `v` the wall-ms
            # verdict stamp the alignment test pins against the
            # engine's frame-drain span.
            t_v = _span_wall_ms()
            spj.record(
                "admit", "worker", t_join, t_v - t_join,
                wid=self.worker_id, seq=seq,
                push_ms=round(t_push - t_join, 3),
                v=round(t_v, 3),
                win=int(self.window_armed), adm=int(out.admitted),
                trace=(trace.trace_id if trace is not None else None),
            )
        return out

    def bulk(
        self,
        resource: str,
        n: int,
        ts=None,
        acquire=1,
        context_name: str = "",
        origin: str = "",
        entry_type: int = 1,  # EntryType.OUT, like the engine API
        args_column: Optional[Sequence] = None,
        timeout_ms: Optional[int] = None,
    ):
        """One pre-grouped columnar group (the worker-side
        ``submit_bulk``): returns dense ``(admitted, reason, wait_ms,
        flags)`` arrays of length n. Groups larger than one slot's
        frame budget split transparently — by BYTES, not rows: args
        payloads count toward the slot budget, so an args-heavy group
        just splits into more frames instead of building one the ring
        can never accept (which would read as phantom ring
        backpressure). A single row whose args alone exceed the budget
        raises ValueError — that is a config/caller mismatch, not
        backpressure."""
        if n < 1:
            raise ValueError("bulk: n must be >= 1")
        _check_entry_type(entry_type)
        alive = self.engine_alive()
        if self._in_handoff:
            alive = self._handoff_hold()
        if not alive:
            v = self._policy_verdict(resource)
            return _dense(n, v)
        ts_col = np.broadcast_to(
            np.asarray(-1 if ts is None else ts, dtype=np.int64), (n,)
        )
        acq_col = np.broadcast_to(
            np.asarray(acquire, dtype=np.int32), (n,)
        )
        budget = self.channel.slot_bytes - fr.FRAME_RESERVE
        args_blobs: Optional[List[bytes]] = None
        if args_column is not None:
            args_blobs = [fr.encode_args(a) for a in args_column]
        chunks = _byte_chunks(
            [
                fr.ENTRY_ROW_BYTES
                + (len(args_blobs[j]) if args_blobs is not None else 0)
                for j in range(n)
            ],
            budget, "bulk",
        )
        out_a = np.zeros(n, dtype=bool)
        out_r = np.zeros(n, dtype=np.int16)
        out_w = np.zeros(n, dtype=np.int32)
        out_f = np.zeros(n, dtype=np.uint8)
        if self.window_armed:
            # Micro-window ride: the whole group joins the assembling
            # window (the flusher re-chunks by bytes across EVERYTHING
            # in the window); per-row budget was validated above.
            with self._lock:
                base = self._seq
                self._seq += n
                rid = self._intern_locked(resource)
                cid = self._intern_locked(context_name)
                oid = self._intern_locked(origin)
                rows = [
                    fr.EntryRow(
                        seq=base + j,
                        resource_id=rid, context_id=cid, origin_id=oid,
                        entry_type=int(entry_type),
                        acquire=int(acq_col[j]),
                        ts=int(ts_col[j]),
                        trace=fr.EMPTY_TRACE,
                        args=(
                            args_blobs[j] if args_blobs is not None else b""
                        ),
                    )
                    for j in range(n)
                ]
                w = _Waiter(n)
                for j in range(n):
                    self._waiters[base + j] = w
                self._win_bulk.update(range(base, base + n))
                self._win_join_locked(rows=rows)
            # bulk_rows counts at flush time (see _win_flush_locked) —
            # per-call parity: a shed window never counts.
            got = self._await_many(
                w, range(base, base + n), resource, timeout_ms,
                live_base=(resource, context_name, origin,
                           int(entry_type)),
                acq=acq_col,
            )
            for j, (adm, rsn, wms, fl) in enumerate(got):
                out_a[j] = adm
                out_r[j] = rsn
                out_w[j] = wms
                out_f[j] = fl
            return out_a, out_r, out_w, out_f
        spj = self._spans
        for lo, hi in chunks:
            m = hi - lo
            t_join = _span_wall_ms() if spj.enabled else 0.0
            with self._lock:
                base = self._seq
                self._seq += m
                rid = self._intern_locked(resource)
                cid = self._intern_locked(context_name)
                oid = self._intern_locked(origin)
                rows = [
                    fr.EntryRow(
                        seq=base + j,
                        resource_id=rid, context_id=cid, origin_id=oid,
                        entry_type=int(entry_type),
                        acquire=int(acq_col[lo + j]),
                        ts=int(ts_col[lo + j]),
                        trace=fr.EMPTY_TRACE,
                        args=(
                            args_blobs[lo + j]
                            if args_blobs is not None else b""
                        ),
                    )
                    for j in range(m)
                ]
                w = _Waiter(m)
                for j in range(m):
                    self._waiters[base + j] = w
                ok = self._push_locked(
                    lambda interns: fr.encode_entries(
                        self.worker_id, rows, interns, self._intern_gen,
                        self._shed_total, kind=fr.KIND_BULK,
                    )
                )
                if not ok:
                    for j in range(m):
                        del self._waiters[base + j]
            if not ok:
                sv = self._shed_verdict(m)
                out_a[lo:hi] = sv.admitted
                out_r[lo:hi] = sv.reason
                continue
            self.counters["bulk_rows"] += m
            got = self._await_many(
                w, range(base, base + m), resource, timeout_ms,
                live_base=(resource, context_name, origin,
                           int(entry_type)),
                acq=acq_col[lo:hi],
            )
            for j, (adm, rsn, wms, fl) in enumerate(got):
                out_a[lo + j] = adm
                out_r[lo + j] = rsn
                out_w[lo + j] = wms
                out_f[lo + j] = fl
            if spj.enabled:
                t_v = _span_wall_ms()
                spj.record(
                    "admit.bulk", "worker", t_join, t_v - t_join,
                    wid=self.worker_id, seq=base, rows=m,
                    v=round(t_v, 3),
                )
        return out_a, out_r, out_w, out_f

    def exit(
        self,
        resource: str,
        context_name: str = "",
        origin: str = "",
        entry_type: int = 1,  # EntryType.OUT, like the engine API
        rt: int = 0,
        count: int = 1,
        err: int = 0,
        ts: Optional[int] = None,
        speculative: Optional[bool] = None,
    ) -> bool:
        """One completion. Never shed: retries a full ring with a short
        backoff, dropping only once the engine is gone (False).

        The (resource, context, origin, entry_type) identity MUST
        match the entry's — it is how the engine-side plane resolves
        the node rows to release and how the live-admission ledger
        pairs the completion with its admit (a mismatched identity
        releases the wrong rows AND leaves the ledger entry live for a
        spurious dead-worker release later). The in-process API has
        the same contract, just structural: there the caller passes
        the entry's ``rows`` tuple back.

        One bounded exception to "never dropped while the engine
        lives": a ring that stays full past ``timeout.ms`` with a
        still-heartbeating engine means the DRAINER is wedged (the
        control thread beats independently) — the completion is then
        dropped and counted in ``exits_dropped`` rather than pinning
        this caller thread forever; the dead-worker reap releases the
        admission once this worker eventually exits.

        With the micro-window armed the completion instead buffers for
        the next window flush and this returns True immediately (=
        accepted for delivery; the flusher applies the same bounded
        retry-then-drop stance on the caller's behalf)."""
        _check_entry_type(entry_type)
        if self.window_armed:
            with self._lock:
                self._win_join_locked(exits=[(
                    resource, context_name, origin, int(entry_type),
                    -1 if ts is None else int(ts),
                    int(rt), int(count), int(err),
                    0 if speculative is None else (1 if speculative else 2),
                )])
            return True
        deadline = time.monotonic() + self.timeout_ms / 1e3
        delay = 0.0002
        spec_wire = 0 if speculative is None else (1 if speculative else 2)
        if self.reconnect_enabled and not self.engine_alive():
            # A frame pushed into a DEAD engine's ring is dead-world
            # backlog the next plane must (and does) drop — buffer the
            # completion for replay after the hot-restart instead.
            with self._lock:
                self._buffer_dead_exits_locked([(
                    resource, context_name, origin, int(entry_type),
                    -1 if ts is None else int(ts),
                    int(rt), int(count), int(err), spec_wire,
                )])
            return True
        while True:
            # (Re)build under the lock on EVERY attempt: a failed push
            # rolled its fresh interns back, so a retried payload must
            # re-intern (carrying stale ids the plane never learned
            # would decode-drop the completion).
            with self._lock:
                seq = self._seq
                self._seq += 1
                row = fr.ExitRow(
                    seq=seq,
                    resource_id=self._intern_locked(resource),
                    context_id=self._intern_locked(context_name),
                    origin_id=self._intern_locked(origin),
                    entry_type=int(entry_type),
                    ts=-1 if ts is None else int(ts),
                    rt=int(rt), count=int(count), err=int(err),
                    spec=spec_wire,
                )
                ok = self._push_locked(
                    lambda interns: fr.encode_exits(
                        self.worker_id, [row], interns, self._intern_gen,
                        self._shed_total,
                    )
                )
                if ok and self.reconnect_enabled:
                    self._live_forget_locked(
                        resource, context_name, origin, int(entry_type),
                        spec_wire, int(count),
                    )
            if ok:
                self.counters["exits"] += 1
                return True
            if not self.engine_alive():
                if self.reconnect_enabled:
                    # Buffer for replay after a hot-restart — the
                    # ledger line stays live so the re-assertion covers
                    # the admission and the replayed exit pairs.
                    with self._lock:
                        self._buffer_dead_exits_locked([(
                            resource, context_name, origin,
                            int(entry_type), -1 if ts is None else int(ts),
                            int(rt), int(count), int(err), spec_wire,
                        )])
                    return True
                with self._lock:
                    self.counters["exits_dropped"] += 1
                return False
            if time.monotonic() > deadline:
                with self._lock:
                    self.counters["exits_dropped"] += 1
                    if self.reconnect_enabled:
                        self._live_forget_locked(
                            resource, context_name, origin,
                            int(entry_type), spec_wire, int(count),
                        )
                return False
            time.sleep(delay)
            delay = min(delay * 2, 0.005)

    # ------------------------------------------------------------------
    # waiting
    # ------------------------------------------------------------------
    def _await_one(
        self, w: _Waiter, seq: int, resource: str,
        timeout_ms: Optional[int], live_ident: Optional[tuple] = None,
    ) -> fr.IpcVerdict:
        t = (timeout_ms or self.timeout_ms) / 1e3
        deadline = time.monotonic() + t
        while True:
            if w.event.wait(timeout=0.05):
                v = w.verdicts.get(seq)
                if v is not None:
                    out = _to_verdict(v)
                    if (
                        self.reconnect_enabled
                        and out.admitted
                        and live_ident is not None
                    ):
                        # Engine-decided admit: one live ledger line
                        # until its completion pairs (policy verdicts
                        # below never reached the engine — no line).
                        res_, ctx_, org_, et_, acq_ = live_ident
                        with self._lock:
                            self._live_note_locked(
                                (res_, ctx_, org_, et_,
                                 out.speculative or out.degraded, acq_)
                            )
                    return out
                w.event.clear()
            # During a planned handoff the stale wall (and the exiting
            # old engine's pid) must not convert a parked caller into a
            # policy verdict — the old world answers in-flight frames
            # before detaching; only the deadline bounds the wait.
            # engine_alive() itself refreshes _in_handoff.
            if time.monotonic() > deadline or (
                not self.engine_alive() and not self._in_handoff
            ):
                with self._lock:
                    self._waiters.pop(seq, None)
                return self._policy_verdict(resource)

    def _await_many(
        self, w: _Waiter, seqs, resource: str, timeout_ms: Optional[int],
        live_base: Optional[tuple] = None, acq=None,
    ) -> List[tuple]:
        t = (timeout_ms or self.timeout_ms) / 1e3
        deadline = time.monotonic() + t
        while True:
            if w.event.wait(timeout=0.05):
                if len(w.verdicts) >= w.need:
                    break
                w.event.clear()
            if time.monotonic() > deadline or (
                not self.engine_alive() and not self._in_handoff
            ):
                break
        with self._lock:
            for s in seqs:
                self._waiters.pop(s, None)
        out = []
        notes: List[tuple] = []
        pol = None
        for i, s in enumerate(seqs):
            v = w.verdicts.get(s)
            if v is None:
                if pol is None:
                    p = self._policy_verdict(resource)
                    pol = (
                        1 if p.admitted else 0, p.reason, 0,
                        fr.F_DEGRADED,
                    )
                v = pol
            elif (
                self.reconnect_enabled and live_base is not None and v[0]
            ):
                res_, ctx_, org_, et_ = live_base
                notes.append(
                    (res_, ctx_, org_, et_,
                     bool(v[3] & (fr.F_SPECULATIVE | fr.F_DEGRADED)),
                     int(acq[i]) if acq is not None else 1)
                )
            out.append(v)
        if notes:
            with self._lock:
                for k in notes:
                    self._live_note_locked(k)
        return out

    def _read_loop(self) -> None:
        park = 0.0005
        spj = self._spans
        while not self._stop.is_set():
            payloads = self.response.pop_all(limit=64)
            if not payloads:
                if self.adaptive_wakeup:
                    # Spin-then-park: the verdict frame usually lands
                    # within the spin; the park (doorbell-ended, timeout
                    # growing to the cap) bounds idle burn.
                    if spj.enabled:
                        t_p = _span_wall_ms()
                        if self.response.wait_readable(self._spin_s, park):
                            # Productive wakes only — an idle client
                            # parking forever must not flood the ring.
                            spj.record(
                                "wake", "worker", t_p,
                                _span_wall_ms() - t_p,
                                wid=self.worker_id,
                            )
                        else:
                            park = min(park * 2, self._park_s)
                    elif not self.response.wait_readable(
                        self._spin_s, park
                    ):
                        park = min(park * 2, self._park_s)
                else:
                    time.sleep(0.0002)
                continue
            park = 0.0005
            for p in payloads:
                try:
                    f = fr.decode_frame(p)
                except (ValueError, struct_error):
                    continue
                if f.kind != fr.KIND_VERDICT:
                    continue
                seqs = f.columns["seq"].tolist()
                adm = f.columns["admitted"].tolist()
                rsn = f.columns["reason"].tolist()
                wms = f.columns["wait_ms"].tolist()
                fl = f.columns["flags"].tolist()
                with self._lock:
                    hit: Dict[_Waiter, bool] = {}
                    for i, s in enumerate(seqs):
                        w = self._waiters.pop(s, None)
                        if w is None:
                            continue
                        w.verdicts[s] = (adm[i], rsn[i], wms[i], fl[i])
                        hit[w] = True
                for w in hit:
                    w.event.set()

    def _beat_loop(self) -> None:
        pid = os.getpid()
        while not self._stop.wait(self.heartbeat_ms / 1e3):
            try:
                self.control.beat_worker(self.worker_id, pid)
            except (ValueError, TypeError):
                return
            if self.reconnect_enabled:
                try:
                    self._maybe_reconnect()
                except Exception:
                    from sentinel_tpu.utils.record_log import record_log

                    record_log.error(
                        "[ipc] reconnect attempt failed — retrying on "
                        "the next beat", exc_info=True,
                    )

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def close(self, clear_slot: bool = True) -> None:
        self._stop.set()
        if self._win_thread is not None:
            # Wake the flusher so the final window (buffered rows and
            # completions) ships before the rings close.
            with self._win_cond:
                self._win_cond.notify_all()
            self._win_thread.join(timeout=2.0)
            self._win_thread = None
        self._reader.join(timeout=2.0)
        if self._beat is not None:
            self._beat.join(timeout=2.0)
        with self._lock:
            if self._dead_exits:
                # Undeliverable completions die with the client — the
                # plane's dead-worker reap releases their admissions.
                self.counters["exits_dropped"] += len(self._dead_exits)
                self._dead_exits = []
        if clear_slot:
            try:
                self.control.clear_worker(self.worker_id)
            except (ValueError, TypeError):
                pass
        if self._spans.enabled:
            # Final journal spill: a worker's spans must survive its
            # exit for fleetdump to merge.
            try:
                self._spans.spill()
            except OSError:
                pass
        self.request.close()
        self.response.close()
        self.control.close()

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "worker_id": self.worker_id,
                "engine_alive": self.engine_alive(),
                "counters": dict(self.counters),
                "interned": len(self._intern),
                "pending_waits": len(self._waiters),
                "window_armed": self.window_armed,
                "window_ms": self.window_ms,
                "window_max": self.window_max,
                "window_pending": len(self._win_rows) + len(self._win_exits),
                "adaptive_wakeup": self.adaptive_wakeup,
                "reconnect_enabled": self.reconnect_enabled,
                "engine_boot": self._boot,
                "live_admissions": (
                    sum(self._live.values()) + sum(self._live_new.values())
                ),
                "buffered_exits": len(self._dead_exits),
            }


def _check_entry_type(entry_type) -> None:
    # Validate at the API edge: the wire carries a bare int8, and the
    # plane per-row-sheds anything it cannot map back to an EntryType
    # — failing HERE turns a silent shed into the caller's bug report.
    if int(entry_type) not in (0, 1):
        raise ValueError(
            f"entry_type must be 0 (IN) or 1 (OUT), got {entry_type!r}"
        )


def _pid_alive(pid: int) -> bool:
    """Signal-0 probe (same host by shared-memory construction).
    EPERM still means "exists" — a privilege boundary is not death."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    return True


def _ambient_trace():
    from sentinel_tpu.core.context import ContextUtil

    return ContextUtil.get_trace()


def _to_verdict(v: tuple) -> fr.IpcVerdict:
    adm, rsn, wms, fl = v
    return fr.IpcVerdict(
        admitted=bool(adm),
        reason=int(rsn),
        wait_ms=int(wms),
        limit_type="ipc_ring" if rsn == E.BLOCK_SHED else "",
        degraded=bool(fl & fr.F_DEGRADED),
        speculative=bool(fl & fr.F_SPECULATIVE),
    )


def _dense(n: int, v: fr.IpcVerdict):
    fl = (fr.F_SPECULATIVE if v.speculative else 0) | (
        fr.F_DEGRADED if v.degraded else 0
    )
    return (
        np.full(n, v.admitted, dtype=bool),
        np.full(n, v.reason, dtype=np.int16),
        np.full(n, v.wait_ms, dtype=np.int32),
        np.full(n, fl, dtype=np.uint8),
    )
