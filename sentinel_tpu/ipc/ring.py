"""Fixed-slot shared-memory rings + the plane control header.

Transport primitives of the multi-process ingest plane — no engine
imports, no pickle anywhere on the data path.

**Ring** (:class:`ShmRing`): a bounded ring of fixed-size slots over
one ``multiprocessing.shared_memory`` segment, with a seqlock-style
``seq`` word heading every slot (the Vyukov bounded-queue discipline):

* init: ``slot[i].seq = i``;
* producer: claim position ``pos``, wait for ``seq == pos`` (slot
  free), write payload length + bytes, then publish ``seq = pos + 1``;
* consumer: at position ``pos``, ``seq == pos + 1`` means a published
  payload — read it, then release with ``seq = pos + slots`` so the
  producer lapping the ring finds it free.

The ``seq`` publish/observe pair is the ordering fence: a consumer
never reads a payload before its producer finished writing it, and a
producer never overwrites one before its consumer finished reading.
``seq`` words are 8-byte-aligned and written with one ``memcpy`` — on
the platforms this targets (Linux x86-64 / aarch64) an aligned 8-byte
store is not torn, which is the same assumption every shared-memory
seqlock makes.

Python has no cross-process atomic fetch-add, so the **MPSC** request
ring serializes only the producer *claim* (advance the shared head
word, check capacity) under a ``multiprocessing.Lock``; payload writes
and the seq publish happen outside it, and the single consumer never
touches the lock at all. The **SPSC** response rings have one producer
by construction and skip the lock entirely.

A full ring never blocks a producer: ``try_push`` returns False and
the caller sheds locally (the worker's ``BLOCK_SHED`` with cause
``ipc_ring`` — backpressure is an admission verdict here, not a
stall).

**Control header** (:class:`ControlBlock`): one small segment holding
the engine health word + heartbeat epoch, the intern-table generation,
one heartbeat/pid slot per worker, and a seqlock-guarded
failover-policy snapshot blob (what workers serve from when the engine
dies). All fields are single 8-byte words except the policy blob,
which carries its own generation pair (read: gen, bytes, gen again —
retry on mismatch/odd).
"""

from __future__ import annotations

import json
import struct
import time
from multiprocessing import shared_memory
from typing import Dict, Optional, Tuple

# ---------------------------------------------------------------------------
# ring layout
# ---------------------------------------------------------------------------
# Ring header: head (u64, producer claim position), tail (u64, consumer
# publish — occupancy reads only), parked (u64, consumer park flag for
# the adaptive-wakeup doorbell), geometry (u32 slots + u32 slot_bytes,
# written at create so a hot-restart attach can VALIDATE instead of
# trusting its own config), then padding to one cache line.
_RING_HDR = 64
_PARKED_OFF = 16
_GEOM_OFF = 24
# Slot header: seq (u64), payload length (u32), pad (u32).
_SLOT_HDR = 16

_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")


def _pow2(n: int) -> int:
    n = max(2, int(n))
    return 1 << (n - 1).bit_length()


class ShmRing:
    """One bounded fixed-slot ring over a shared-memory segment.

    ``create=True`` owns the segment (and unlinks it on ``destroy()``);
    attachers open by name. ``lock`` (a ``multiprocessing.Lock``) is
    required only on multi-producer rings — pass None for SPSC.

    ``doorbell`` (a ``multiprocessing.Semaphore``, optional) arms the
    **adaptive wakeup** protocol: the consumer parks on the semaphore
    after a bounded spin (``wait_readable``), advertising the park
    through the shared ``parked`` header word; a producer that
    publishes while the flag is up rings the doorbell. The flag
    re-check after parking closes the set-flag/publish race (no lost
    wakeup), and a release racing an un-parked consumer just leaves a
    token the next park consumes as a spurious-but-harmless early wake.
    Without a doorbell (the default) nothing here changes: the parked
    word stays 0 and ``try_push`` pays one attribute read.
    """

    def __init__(
        self,
        name: Optional[str],
        slots: int,
        slot_bytes: int,
        create: bool = False,
        lock=None,
        doorbell=None,
    ) -> None:
        self.slots = _pow2(slots)
        self.slot_bytes = int(slot_bytes)
        self._mask = self.slots - 1
        self._stride = _SLOT_HDR + self.slot_bytes
        self._lock = lock
        self._doorbell = doorbell
        size = _RING_HDR + self.slots * self._stride
        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=size
            )
        else:
            self.shm = shared_memory.SharedMemory(name=name)
        self._buf = self.shm.buf
        self.name = self.shm.name
        self._owner = create
        if create:
            self._buf[:size] = b"\x00" * size
            _U32.pack_into(self._buf, _GEOM_OFF, self.slots)
            _U32.pack_into(self._buf, _GEOM_OFF + 4, self.slot_bytes)
            for i in range(self.slots):
                self._seq_write(i, i)
        else:
            # Geometry validation on attach (engine hot-restart: a new
            # process re-attaching with a DIFFERENT configured geometry
            # would mis-stride every slot — corrupt silently, so fail
            # loudly instead). Zero = pre-geometry segment; trust the
            # caller like PR-13/14 did.
            g_slots = _U32.unpack_from(self._buf, _GEOM_OFF)[0]
            g_bytes = _U32.unpack_from(self._buf, _GEOM_OFF + 4)[0]
            if g_slots and (g_slots, g_bytes) != (self.slots, self.slot_bytes):
                name = self.name
                self._buf = None  # release the view before close()
                try:
                    self.shm.close()
                except (OSError, BufferError):
                    pass
                raise ValueError(
                    f"ring geometry mismatch: segment {name} has "
                    f"{g_slots}x{g_bytes}B slots, attach asked "
                    f"{self.slots}x{self.slot_bytes}B"
                )
        # Consumer-local read position (the consumer is the only reader
        # of its own ring, so this needs no shared state beyond `tail`).
        self._rpos = self._tail_read()
        # Claimed-but-never-published slot watch (a producer killed
        # between claim and publish would wedge the consumer forever):
        # (position, first-observed monotonic time).
        self._stall: Optional[Tuple[int, float]] = None

    # -- raw word access ------------------------------------------------
    def _seq_off(self, idx: int) -> int:
        return _RING_HDR + idx * self._stride

    def _seq_read(self, idx: int) -> int:
        off = self._seq_off(idx)
        return _U64.unpack_from(self._buf, off)[0]

    def _seq_write(self, idx: int, v: int) -> None:
        _U64.pack_into(self._buf, self._seq_off(idx), v & 0xFFFFFFFFFFFFFFFF)

    def _head_read(self) -> int:
        return _U64.unpack_from(self._buf, 0)[0]

    def _head_write(self, v: int) -> None:
        _U64.pack_into(self._buf, 0, v)

    def _tail_read(self) -> int:
        return _U64.unpack_from(self._buf, 8)[0]

    def _tail_write(self, v: int) -> None:
        _U64.pack_into(self._buf, 8, v)

    # -- producer -------------------------------------------------------
    def try_push(self, payload: bytes) -> bool:
        """Publish one payload; False when the ring is full (caller
        sheds) or the payload exceeds the slot size (caller must split
        — the frame codec enforces this earlier)."""
        n = len(payload)
        if n > self.slot_bytes:
            return False
        try:
            if self._lock is not None:
                with self._lock:
                    pos = self._claim()
            else:
                pos = self._claim()
        except (TypeError, ValueError):
            return False  # ring released by a concurrent close()
        if pos is None:
            return False
        idx = pos & self._mask
        off = self._seq_off(idx)
        _U32.pack_into(self._buf, off + 8, n)
        self._buf[off + _SLOT_HDR : off + _SLOT_HDR + n] = payload
        # The publish: consumers spin on seq == pos + 1.
        self._seq_write(idx, pos + 1)
        d = self._doorbell
        if d is not None:
            # Adaptive wakeup: ring only when the consumer advertised a
            # park — the common (unparked) case costs one 8-byte read.
            try:
                if _U64.unpack_from(self._buf, _PARKED_OFF)[0]:
                    d.release()
            except (TypeError, ValueError):
                pass  # ring released by a concurrent close()
        return True

    def _claim(self) -> Optional[int]:
        pos = self._head_read()
        # Full when the claimed slot has not been released by the
        # consumer yet (its seq still belongs to the previous lap).
        if self._seq_read(pos & self._mask) != pos:
            return None
        self._head_write(pos + 1)
        return pos

    # -- consumer -------------------------------------------------------
    def try_pop(self) -> Optional[bytes]:
        """One published payload (a bytes COPY — the slot recycles the
        moment this returns), or None when the ring is empty."""
        pos = self._rpos
        idx = pos & self._mask
        try:
            if self._seq_read(idx) != pos + 1:
                return None
        except (TypeError, ValueError):
            return None  # ring released by a concurrent close()
        off = self._seq_off(idx)
        n = _U32.unpack_from(self._buf, off + 8)[0]
        payload = bytes(self._buf[off + _SLOT_HDR : off + _SLOT_HDR + n])
        # Release for the producer's next lap, then publish tail for
        # occupancy readers.
        self._seq_write(idx, pos + self.slots)
        self._rpos = pos + 1
        self._tail_write(self._rpos)
        return payload

    def pop_all(self, limit: int = 0) -> list:
        out = []
        while True:
            p = self.try_pop()
            if p is None:
                return out
            out.append(p)
            if limit and len(out) >= limit:
                return out

    def maybe_skip_stalled(self, age_s: float) -> bool:
        """Consumer-side dead-producer recovery: when the head has
        advanced past the read position but the slot there was never
        published (claimed, then the producer died mid-write — e.g. a
        ``kill -9`` worker), release the slot and step over it once the
        stall has persisted for ``age_s``. A merely-slow producer
        finishes its ``memcpy`` in microseconds, so an ``age_s`` in the
        worker-death range can only ever skip a corpse's slot. Returns
        True when a slot was skipped (the frame it would have carried
        is lost — its caller's verdict wait times out into the
        engine-death path, which is the survivable outcome).

        Any value other than the published ``pos + 1`` counts as
        stalled — not just the untouched claim value ``pos``. The
        extra case is a producer suspended long enough to be skipped
        ONCE and then waking to publish its stale lap's ``seq``: that
        write would otherwise poison the slot for every future lap
        (no claim ever matches again and the ring reads full forever),
        so the aged skip here is also the recovery path for it."""
        pos = self._rpos
        idx = pos & self._mask
        if self._head_read() <= pos or self._seq_read(idx) == pos + 1:
            self._stall = None
            return False
        now = time.monotonic()
        if self._stall is None or self._stall[0] != pos:
            self._stall = (pos, now)
            return False
        if now - self._stall[1] < age_s:
            return False
        self._seq_write(idx, pos + self.slots)
        self._rpos = pos + 1
        self._tail_write(self._rpos)
        self._stall = None
        return True

    # -- adaptive wakeup (consumer side) --------------------------------
    def readable(self) -> bool:
        """True when a published payload is waiting at the read
        position — the consumer's spin predicate (one aligned 8-byte
        read; False once the ring is closed)."""
        pos = self._rpos
        try:
            return self._seq_read(pos & self._mask) == pos + 1
        except (TypeError, ValueError):
            return False

    def wait_readable(self, spin_s: float, park_s: float) -> bool:
        """Spin-then-park consumer wait: busy-check ``readable`` for up
        to ``spin_s`` (keeps the hot round trip off the scheduler),
        then park on the doorbell for up to ``park_s``. The parked flag
        is re-checked against a publish that raced the park, so a
        producer's doorbell ring is never lost; a ring without a
        doorbell just reports the spin outcome (the caller falls back
        to its sleep strategy). Returns ``readable()`` at exit."""
        deadline = time.monotonic() + spin_s
        while True:
            if self.readable():
                return True
            if time.monotonic() >= deadline:
                break
        d = self._doorbell
        if d is None:
            return False
        try:
            _U64.pack_into(self._buf, _PARKED_OFF, 1)
        except (TypeError, ValueError):
            return False
        try:
            # Close the park/publish race: a producer that published
            # BEFORE seeing the flag rings no doorbell — it must be
            # caught here, not slept past.
            if self.readable():
                return True
            d.acquire(timeout=park_s)
            return self.readable()
        finally:
            try:
                _U64.pack_into(self._buf, _PARKED_OFF, 0)
            except (TypeError, ValueError):
                pass

    def nudge(self) -> None:
        """Ring the consumer doorbell unconditionally (worker-side
        death-confirmation probe: wake a parked drainer so a merely
        idle engine beats before the declaration lands). At most one
        spurious consumer wake per call; no-op without a doorbell."""
        d = self._doorbell
        if d is None:
            return
        try:
            d.release()
        except (OSError, ValueError):
            pass

    # -- readers --------------------------------------------------------
    def occupancy(self) -> float:
        """Published head minus published tail over capacity (0..1) —
        an advisory read for metrics and capacity checks. Returns 0
        once the ring is closed: a Prometheus scrape racing
        ``close()``/``destroy()`` during shutdown must degrade, not
        fail the whole render."""
        try:
            used = self._head_read() - self._tail_read()
        except (TypeError, ValueError):
            return 0.0  # _buf already released by close()
        return min(1.0, max(0.0, used / float(self.slots)))

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        self._buf = None
        try:
            self.shm.close()
        except (OSError, BufferError):
            pass

    def destroy(self) -> None:
        self.close()
        if self._owner:
            try:
                self.shm.unlink()
            except OSError:
                pass


# ---------------------------------------------------------------------------
# control header
# ---------------------------------------------------------------------------
# Layout (all offsets in bytes):
#   0   u32 magic, u32 version
#   8   u64 engine heartbeat epoch (monotonically bumped by the plane)
#   16  u64 engine health word (HEALTH_*)
#   24  u64 intern-table generation (bump invalidates every worker's
#       local string->id dict; workers re-intern on their next frame)
#   32  u64 engine wall-clock ms at the last heartbeat (staleness ruler
#       for workers — epoch deltas alone need a shared cadence)
#   40  u64 engine BOOT epoch: bumped once per plane attach/create —
#       the hot-restart generation word. A worker that sees it change
#       re-interns, re-asserts its live-admission ledger and replays
#       buffered completions (ipc/worker.py reconnect protocol).
#   48  u32 workers_max at create (attach validates geometry)
#   52  u32 engine pid (written at plane attach; the worker-side
#       liveness CONFIRMATION ruler — a stale wall clock plus a live
#       pid means "pegged, not dead", ipc/worker.py)
#   56  .. reserved to 64
#   64  worker slots: WORKERS_MAX x 32 bytes
#       [u64 heartbeat epoch, u64 wall ms, u32 pid, u32 shed count,
#        u64 reserved]
#   ..  policy blob: u64 generation, u32 length, pad, POLICY_CAP bytes
_MAGIC = 0x53544950  # "PITS" — sentinel-tpu ipc
_VERSION = 1
_CTRL_FIXED = 64
_WSLOT = 32
POLICY_CAP = 4096

HEALTH_HEALTHY = 0
HEALTH_DEGRADED = 1
HEALTH_CLOSED = 2
# Planned-handoff drain: the OLD engine is alive and settling in-flight
# work but accepts no NEW admissions — workers hold (bounded) for the
# successor's boot-epoch bump instead of falling to the policy path.
HEALTH_HANDOFF = 3

HEALTH_NAMES = {
    HEALTH_HEALTHY: "HEALTHY",
    HEALTH_DEGRADED: "DEGRADED",
    HEALTH_CLOSED: "CLOSED",
    HEALTH_HANDOFF: "HANDOFF",
}


def _wall_ms() -> int:
    return int(time.time() * 1000)


def resolve_spin_us(v: int) -> int:
    """The adaptive-wakeup spin bound: ``v`` >= 0 verbatim; -1 (the
    config default) auto-picks by core count — 0 on <=2-core hosts
    (spinning steals the core the other side of the pipe needs; pure
    doorbell park measured 2x faster there) and 50 µs where producer
    and consumer can genuinely run concurrently."""
    if v >= 0:
        return int(v)
    import os

    return 0 if (os.cpu_count() or 1) <= 2 else 50


class ControlBlock:
    """The plane's shared control header (see module doc for layout)."""

    def __init__(
        self, name: Optional[str], workers_max: int, create: bool = False
    ) -> None:
        self.workers_max = max(1, int(workers_max))
        self._policy_off = _CTRL_FIXED + self.workers_max * _WSLOT
        size = self._policy_off + 16 + POLICY_CAP
        if create:
            self.shm = shared_memory.SharedMemory(
                name=name, create=True, size=size
            )
            self.shm.buf[:size] = b"\x00" * size
            _U32.pack_into(self.shm.buf, 0, _MAGIC)
            _U32.pack_into(self.shm.buf, 4, _VERSION)
            _U32.pack_into(self.shm.buf, 48, self.workers_max)
        else:
            self.shm = shared_memory.SharedMemory(name=name)
            magic = _U32.unpack_from(self.shm.buf, 0)[0]
            ver = _U32.unpack_from(self.shm.buf, 4)[0]
            if magic != _MAGIC or ver != _VERSION:
                self.shm.close()
                raise ValueError(
                    f"not an ipc control segment (magic {magic:#x}, "
                    f"version {ver})"
                )
            wm = _U32.unpack_from(self.shm.buf, 48)[0]
            if wm and wm != self.workers_max:
                # Hot-restart attach with a different workers.max would
                # mis-place every worker slot and the policy blob.
                self.shm.close()
                raise ValueError(
                    f"control geometry mismatch: segment has "
                    f"workers_max={wm}, attach asked {self.workers_max}"
                )
        self._buf = self.shm.buf
        self.name = self.shm.name
        self._owner = create

    # -- engine side ----------------------------------------------------
    def beat_engine(self, health: int) -> None:
        epoch = _U64.unpack_from(self._buf, 8)[0] + 1
        _U64.pack_into(self._buf, 8, epoch)
        _U64.pack_into(self._buf, 16, health)
        _U64.pack_into(self._buf, 32, _wall_ms())

    def set_health(self, health: int) -> None:
        _U64.pack_into(self._buf, 16, health)

    def bump_intern_gen(self) -> int:
        gen = _U64.unpack_from(self._buf, 24)[0] + 1
        _U64.pack_into(self._buf, 24, gen)
        return gen

    def bump_engine_boot(self) -> int:
        """Advance the hot-restart generation word — called once per
        plane attach/create; workers react to the CHANGE (reconnect
        protocol), so the absolute value doubles as a restart count."""
        boot = _U64.unpack_from(self._buf, 40)[0] + 1
        _U64.pack_into(self._buf, 40, boot)
        return boot

    def engine_boot(self) -> int:
        """Current boot epoch; 0 once the header is released (a worker
        racing close() must not see a phantom restart)."""
        try:
            return _U64.unpack_from(self._buf, 40)[0]
        except (TypeError, ValueError):
            return 0

    def set_engine_pid(self, pid: int) -> None:
        """Publish the engine process id (written once per plane
        attach) — the death-confirmation probe target for workers."""
        _U32.pack_into(self._buf, 52, pid & 0xFFFFFFFF)

    def engine_pid(self) -> int:
        try:
            return _U32.unpack_from(self._buf, 52)[0]
        except (TypeError, ValueError):
            return 0

    def publish_policy(self, default: str, overrides: Dict[str, str]) -> bool:
        """Seqlock-write the failover-policy snapshot. Overrides that
        do not fit POLICY_CAP are dropped largest-name-last (the
        default still applies to them — a bounded header cannot carry
        unbounded per-resource state); returns False when truncated."""
        items = sorted(overrides.items(), key=lambda kv: len(kv[0]))
        complete = True
        while True:
            blob = json.dumps(
                {"default": default, "overrides": dict(items)},
                separators=(",", ":"),
            ).encode("utf-8")
            if len(blob) <= POLICY_CAP:
                break
            items = items[:-1]
            complete = False
        off = self._policy_off
        gen = _U64.unpack_from(self._buf, off)[0]
        _U64.pack_into(self._buf, off, gen + 1)  # odd: write in progress
        _U32.pack_into(self._buf, off + 8, len(blob))
        self._buf[off + 16 : off + 16 + len(blob)] = blob
        _U64.pack_into(self._buf, off, gen + 2)  # even: published
        return complete

    # -- worker side ----------------------------------------------------
    def _wslot(self, worker_id: int) -> int:
        if not (0 <= worker_id < self.workers_max):
            raise ValueError(f"worker_id {worker_id} out of range")
        return _CTRL_FIXED + worker_id * _WSLOT

    def beat_worker(self, worker_id: int, pid: int) -> None:
        off = self._wslot(worker_id)
        epoch = _U64.unpack_from(self._buf, off)[0] + 1
        _U64.pack_into(self._buf, off, epoch)
        _U64.pack_into(self._buf, off + 8, _wall_ms())
        _U32.pack_into(self._buf, off + 16, pid & 0xFFFFFFFF)

    def clear_worker(self, worker_id: int) -> None:
        off = self._wslot(worker_id)
        self._buf[off : off + _WSLOT] = b"\x00" * _WSLOT

    def note_worker_shed(self, worker_id: int, n: int) -> None:
        """Worker-local ring-full shed count (cumulative) — the plane
        folds the delta into the engine's IngestValve accounting."""
        off = self._wslot(worker_id) + 20
        cur = _U32.unpack_from(self._buf, off)[0]
        _U32.pack_into(self._buf, off, (cur + n) & 0xFFFFFFFF)

    # -- shared reads ---------------------------------------------------
    def engine_view(self) -> Tuple[int, int, int, int]:
        """(heartbeat epoch, health word, intern generation, wall ms).
        A closed/released header reads as CLOSED — a thread racing
        ``close()`` must see a dead engine, not a TypeError."""
        try:
            return (
                _U64.unpack_from(self._buf, 8)[0],
                _U64.unpack_from(self._buf, 16)[0],
                _U64.unpack_from(self._buf, 24)[0],
                _U64.unpack_from(self._buf, 32)[0],
            )
        except (TypeError, ValueError):
            return (0, HEALTH_CLOSED, 0, 0)

    def intern_gen(self) -> int:
        try:
            return _U64.unpack_from(self._buf, 24)[0]
        except (TypeError, ValueError):
            return 0  # header already released by close()

    def worker_view(self, worker_id: int) -> Tuple[int, int, int, int]:
        """(heartbeat epoch, wall ms, pid, cumulative shed count)."""
        off = self._wslot(worker_id)
        return (
            _U64.unpack_from(self._buf, off)[0],
            _U64.unpack_from(self._buf, off + 8)[0],
            _U32.unpack_from(self._buf, off + 16)[0],
            _U32.unpack_from(self._buf, off + 20)[0],
        )

    def read_policy(self) -> Tuple[str, Dict[str, str]]:
        """Seqlock-read the policy snapshot: (default, overrides).
        Never-published (all-zero) reads as fail-open, matching the
        failover default."""
        off = self._policy_off
        for _ in range(64):
            try:
                g0 = _U64.unpack_from(self._buf, off)[0]
            except (TypeError, ValueError):
                return "open", {}  # header released by close()
            if g0 == 0:
                return "open", {}
            if g0 & 1:
                continue  # write in progress
            n = _U32.unpack_from(self._buf, off + 8)[0]
            blob = bytes(self._buf[off + 16 : off + 16 + min(n, POLICY_CAP)])
            if _U64.unpack_from(self._buf, off)[0] == g0:
                try:
                    d = json.loads(blob.decode("utf-8"))
                    return d.get("default", "open"), d.get("overrides", {})
                except (ValueError, AttributeError):
                    return "open", {}
        return "open", {}

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        self._buf = None
        try:
            self.shm.close()
        except (OSError, BufferError):
            pass

    def destroy(self) -> None:
        self.close()
        if self._owner:
            try:
                self.shm.unlink()
            except OSError:
                pass
