"""Multi-process ingest plane: shared-memory columnar rings feeding one
engine.

Sentinel's product shape is "many request-serving threads, one
admission authority"; here the authority is the device engine, and the
columnar ingest spine (runtime/window.py) lets ONE process saturate it
— but host-side adapter encode is GIL-bound Python, so a single
front-end process is the scaling wall. This package makes the front
end horizontally scalable the way data-plane sketch systems split
front-end from authority (HashPipe, arXiv:1611.04825): N worker
processes encode admissions into a shared-memory **MPSC request ring**,
the engine process drains frames onto the existing columnar
``submit_bulk``/BatchWindow spine, and verdicts fan back through one
**SPSC response ring per worker** — pickle-free both ways.

Modules:

* :mod:`~sentinel_tpu.ipc.ring` — fixed-slot rings over
  ``multiprocessing.shared_memory`` with seqlock-style slot headers,
  plus the control header (engine health word + heartbeat, per-worker
  heartbeat epochs, intern-table generation, failover-policy snapshot).
* :mod:`~sentinel_tpu.ipc.frames` — the columnar frame codec: fixed
  numpy columns for ts/acquire/entry-type/origin/resource ids and the
  packed W3C traceparent, a varbytes region for args, and the
  per-connection intern protocol (each string crosses the boundary
  once).
* :mod:`~sentinel_tpu.ipc.worker` — :class:`IngestClient`, the
  entry/exit/bulk API workers speak. The client holds no device state
  and does no jax work — a worker process only ever touches numpy and
  shared memory. Its micro-window
  (``sentinel.tpu.ipc.client.window.*``) coalesces concurrent calls
  into one frame per bounded window.
* :mod:`~sentinel_tpu.ipc.plane` — :class:`IngestPlane`, the
  engine-side drainer.
* :mod:`~sentinel_tpu.ipc.worker_mode` — worker mode
  (``sentinel.tpu.ipc.worker.mode``): route a whole process's
  ``api.entry`` surface (and therefore every adapter) through its
  client; ``api.run_workers`` / ``tools/ipc_launch.py`` make an
  N-process deployment one line.

Config lives under ``sentinel.tpu.ipc.*`` (utils/config.py); the plane
is **off by default** — never constructed, no shared memory, at most
one attribute read on any engine hot path.
"""

from sentinel_tpu.ipc.frames import IpcVerdict  # noqa: F401
from sentinel_tpu.ipc.worker import IngestClient  # noqa: F401

__all__ = ["IngestClient", "IpcVerdict"]


def __getattr__(name):
    # IngestPlane pulls in the engine (and therefore jax) — resolve it
    # lazily so `import sentinel_tpu.ipc` stays worker-light.
    if name == "IngestPlane":
        from sentinel_tpu.ipc.plane import IngestPlane

        return IngestPlane
    raise AttributeError(name)
