"""Engine supervision & warm hot-restart for the multi-process plane.

PR 13/14 made one engine process the spine for N worker processes —
and therefore the single point of failure: an engine death left every
worker serving *static* policy-snapshot verdicts forever. This module
closes the loop (the Envoy hot-restart lineage: warm handoff, not cold
start):

* the **supervisor** (this process) owns the named shared-memory
  segments and the cross-process primitives (the MPSC claim lock and
  the adaptive-wakeup doorbells — they cannot live in shared memory,
  so they must belong to a process that OUTLIVES any one engine);
* the **engine child** builds its Engine, loads rules (the ``setup``
  callable), warm-starts from the durable checkpoint
  (``sentinel.tpu.failover.checkpoint.path`` →
  ``FailoverManager.restore_durable``), then attaches an
  :class:`~sentinel_tpu.ipc.plane.IngestPlane` to the EXISTING rings —
  bumping the control header's engine-boot epoch;
* **workers** are ordinary worker-mode children: when the engine dies
  they serve the failover-policy snapshot, and when the epoch bumps
  they re-intern, re-assert their live-admission ledgers and replay
  buffered completions (ipc/worker.py reconnect protocol);
* a crashed engine child is respawned on the shared
  :class:`~sentinel_tpu.datasource.backoff.Backoff`
  (``sentinel.tpu.supervise.backoff.{ms,max.ms}``), bounded by
  ``sentinel.tpu.supervise.restarts.max`` (0 = unlimited).

PR 20 closes the cold-boot gap with a **warm standby** and a
**planned live handoff**:

* with ``sentinel.tpu.supervise.standby.enabled`` the supervisor
  pre-forks a SECOND engine child (``standby_main``) that imports JAX,
  loads rules, warm-compiles the flush kernels via probe batches
  (``FailoverManager.warm_probe``) and re-warms from the durable
  checkpoint every ``standby.warm.interval.ms`` — parked WITHOUT
  attaching to the rings. On primary death the supervisor sends it
  ``attach`` instead of cold-respawning: the measured outage collapses
  from cold-boot seconds to ≈ the detection window, and the NEXT
  standby is pre-forked immediately;
* ``EngineSupervisor.handoff()`` (SIGUSR1 / the ``handoff`` transport
  command) triggers a planned drain: the primary publishes HANDOFF on
  the control header (workers HOLD new admissions instead of serving
  policy verdicts), settles in-flight flushes, spills a final durable
  checkpoint, marks its capture segments orderly-closed and exits with
  ``EXIT_HANDOFF`` — the standby takes over with zero policy-served
  verdicts. This is the mechanism for rolling engine upgrades and
  rule-table recompiles served from the standby.

The public faces are ``api.run_engine_supervised`` (embedders) and
``tools/ipc_launch.py --supervise`` (CLI).
"""

from __future__ import annotations

import multiprocessing
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from sentinel_tpu.utils.config import config

# Exit code of an engine child that completed a PLANNED handoff drain:
# the watcher promotes the standby immediately — no backoff, no restart
# budget spent (an orderly drain is not a crash).
EXIT_HANDOFF = 42


@dataclass
class PlaneHandles:
    """Everything an engine child (and the worker channels) need to
    share one set of named segments across engine restarts. Picklable
    through ``multiprocessing`` spawn — the lock/semaphores travel via
    mp's own reduction, so every consumer must be a DESCENDANT of the
    process that built this (the supervisor)."""

    prefix: str
    workers_max: int
    ring_slots: int
    slot_bytes: int
    resp_slots: int
    n_workers: int
    request_lock: object = field(repr=False, default=None)
    request_doorbell: object = field(repr=False, default=None)
    response_doorbells: Optional[List[object]] = field(
        repr=False, default=None
    )

    def channel(self, worker_id: int):
        """The worker-side attach record for one slot — the supervised
        twin of ``IngestPlane.channel`` (names are deterministic, so no
        plane object is needed here)."""
        from sentinel_tpu.ipc.worker import PlaneChannel

        bells = self.response_doorbells or []
        return PlaneChannel(
            control_name=f"{self.prefix}-ctl",
            request_name=f"{self.prefix}-req",
            response_name=f"{self.prefix}-resp{worker_id}",
            ring_slots=self.ring_slots,
            slot_bytes=self.slot_bytes,
            resp_slots=self.resp_slots,
            workers_max=self.workers_max,
            request_lock=self.request_lock,
            request_doorbell=self.request_doorbell,
            response_doorbell=(
                bells[worker_id] if worker_id < len(bells) else None
            ),
        )


def make_handles(ctx, prefix: str, n_workers: int) -> PlaneHandles:
    """Build the shared primitives from the current config (the
    supervisor side; geometry keys replay into every child)."""
    wake = (config.get(config.IPC_WAKEUP) or "sleep").strip().lower()
    adaptive = wake == "adaptive"
    workers_max = max(1, config.get_int(config.IPC_WORKERS_MAX, 8))
    return PlaneHandles(
        prefix=prefix,
        workers_max=workers_max,
        ring_slots=config.get_int(config.IPC_RING_SLOTS, 1024),
        slot_bytes=max(1024, config.get_int(config.IPC_SLOT_BYTES, 16384)),
        resp_slots=config.get_int(config.IPC_RESP_SLOTS, 1024),
        n_workers=max(0, min(n_workers, workers_max)),
        request_lock=ctx.Lock(),
        request_doorbell=ctx.Semaphore(0) if adaptive else None,
        response_doorbells=(
            [ctx.Semaphore(0) for _ in range(workers_max)]
            if adaptive else None
        ),
    )


def _unlink_stale(name: str) -> None:
    """Remove a leftover segment from a DEAD supervisor incarnation.
    Safe by construction: the engine child and all workers are daemon
    children of the supervisor, so a crashed supervisor takes its whole
    fleet with it — nothing live can still be mapped to these names."""
    from multiprocessing import shared_memory

    try:
        s = shared_memory.SharedMemory(name)
    except (FileNotFoundError, OSError, ValueError):
        return
    try:
        s.close()
        s.unlink()
    except OSError:
        pass


def create_segments(handles: PlaneHandles):
    """Pre-create every named segment from the SUPERVISOR so (a) they
    outlive any one engine process and (b) workers can attach before
    the first engine is even up. A segment left behind by a CRASHED
    supervisor (its own kill -9 is inside this PR's failure domain) is
    unlinked and recreated fresh — the old fleet died with it. Returns
    the owner objects — keep them alive; ``destroy_segments`` unlinks
    at final shutdown."""
    from sentinel_tpu.ipc.ring import ControlBlock, ShmRing

    def fresh(factory, name):
        try:
            return factory()
        except FileExistsError:
            _unlink_stale(name)
            return factory()

    segs = [fresh(
        lambda: ControlBlock(
            f"{handles.prefix}-ctl", handles.workers_max, create=True
        ),
        f"{handles.prefix}-ctl",
    )]
    segs.append(fresh(
        lambda: ShmRing(
            f"{handles.prefix}-req", handles.ring_slots,
            handles.slot_bytes, create=True,
        ),
        f"{handles.prefix}-req",
    ))
    for wid in range(handles.n_workers):
        name = f"{handles.prefix}-resp{wid}"
        segs.append(fresh(
            lambda name=name: ShmRing(
                name, handles.resp_slots, handles.slot_bytes, create=True
            ),
            name,
        ))
    return segs


def destroy_segments(segs) -> None:
    for s in segs:
        try:
            s.destroy()
        except Exception:
            pass


def engine_main(handles: PlaneHandles, overrides, setup, setup_args) -> None:
    """Spawn target: one engine child's whole life. Top-level so
    ``multiprocessing`` spawn children import it by name.

    Order matters: rules first (``setup``), then the durable
    warm-start (restore wants the rule indexes compiled so the
    fingerprints can match), and the plane LAST — workers reconnect
    only once the warm state is installed, so their ledger
    re-assertions land on the restored world, never a half-built one."""
    for k, v in (overrides or {}).items():
        config.set(k, v)
    # This child constructs its plane explicitly from the handles — a
    # replayed ipc.enabled=true must not auto-start a second, anonymous
    # plane inside Engine.__init__.
    config.set(config.IPC_ENABLED, "false")
    from sentinel_tpu.core import api
    from sentinel_tpu.ipc.plane import IngestPlane
    from sentinel_tpu.utils.record_log import record_log

    stop = threading.Event()

    def _on_term(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    eng = api.get_engine()
    if setup is not None:
        try:
            setup(eng, *(setup_args or ()))
        except Exception:
            record_log.error(
                "[supervise] engine setup failed — serving without it",
                exc_info=True,
            )
    if eng.failover.armed and eng.failover.durable_path:
        try:
            eng.failover.restore_durable()
        except Exception:
            # restore_durable itself never raises by contract; this is
            # the last-resort guard — a warm start is an optimization,
            # never a liveness requirement.
            record_log.error(
                "[supervise] durable restore raised — cold start",
                exc_info=True,
            )
    IngestPlane(eng, handles=handles)
    record_log.info(
        "[supervise] engine child up (pid %d, epoch %d)",
        os.getpid(), eng.ipc_plane.engine_epoch,
    )
    raise SystemExit(_serve(eng, stop))


def _serve(eng, stop: threading.Event) -> int:
    """Park an ATTACHED engine child until shutdown. Returns the
    process exit code: 0 for an orderly SIGTERM close, ``EXIT_HANDOFF``
    after a planned handoff drain (SIGUSR1 or the ``handoff`` transport
    command) — the watcher promotes the warm standby on that code
    without touching the restart backoff."""
    handoff_evt = threading.Event()

    def _on_usr1(_sig, _frm):
        handoff_evt.set()

    try:
        signal.signal(signal.SIGUSR1, _on_usr1)
    except (ValueError, OSError):
        pass
    requested = getattr(eng, "handoff_requested", None)
    while not stop.is_set():
        if handoff_evt.is_set() or (
            requested is not None and requested.is_set()
        ):
            _perform_handoff(eng)
            return EXIT_HANDOFF
        stop.wait(0.2)
    eng.close()
    return 0


def _perform_handoff(eng) -> None:
    """The old-world half of a planned handoff, in drain order:
    (1) arm a one-shot checkpoint so the settling flush carries the
    freshest state; (2) ``plane.handoff()`` — publish HANDOFF (workers
    hold), drain the request ring to sustained-empty, detach WITHOUT
    the CLOSED word so the rings and worker ledgers survive for the
    successor; (3) settle in-flight flushes; (4) spill the final
    durable checkpoint synchronously; (5) mark the capture segments
    orderly-closed so the successor's death sweep files them as
    ``frozen-close-*``, not ``frozen-death-*``; (6) close the engine
    (the plane is already detached — no CLOSED is ever published)."""
    from sentinel_tpu.utils.record_log import record_log

    fo = eng.failover
    durable = fo.armed and fo.durable_path
    if durable:
        fo.request_checkpoint()
    plane = eng.ipc_plane
    if plane is not None:
        try:
            stats = plane.handoff()
            record_log.info("[supervise] handoff drain: %s", stats)
        except Exception:
            record_log.error(
                "[supervise] handoff drain failed — closing anyway",
                exc_info=True,
            )
    try:
        eng.flush()
        eng.drain()
    except Exception:
        record_log.error(
            "[supervise] handoff settle failed", exc_info=True
        )
    if durable:
        fo.spill_durable_now()
    if eng.capture is not None:
        try:
            eng.capture.mark_orderly_close("handoff")
        except Exception:
            record_log.error(
                "[supervise] orderly-close marker failed", exc_info=True
            )
    eng.close()


def standby_main(
    handles: PlaneHandles, overrides, setup, setup_args, conn
) -> None:
    """Spawn target: a warm STANDBY engine child. It does everything
    ``engine_main`` does EXCEPT attach: import JAX, load rules,
    warm-start from the durable checkpoint, warm-compile the flush
    kernels via probe batches — then park, re-warming from the durable
    file every ``standby.warm.interval.ms`` until the supervisor sends
    ``attach`` (primary died or drained), at which point it does a
    final restore, re-arms the flight recorder and attaches to the
    existing rings (boot-epoch bump → normal worker reconnect).

    Pipe protocol: child sends ``("ready", warm_boot_ms)`` once
    compiled, ``("attached", attach_ms)`` after the plane is up;
    parent sends ``"attach"`` or ``"stop"``. The flight recorder stays
    DISARMED until promotion — a standby's CaptureJournal would run
    the next-boot death sweep against the LIVE primary's segments in
    the shared capture directory."""
    for k, v in (overrides or {}).items():
        config.set(k, v)
    config.set(config.IPC_ENABLED, "false")
    cap_override = (overrides or {}).get(config.CAPTURE_ENABLED, "")
    config.set(config.CAPTURE_ENABLED, "false")
    from sentinel_tpu.core import api
    from sentinel_tpu.ipc.plane import IngestPlane
    from sentinel_tpu.utils.record_log import record_log

    stop = threading.Event()

    def _on_term(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    t0 = time.perf_counter()
    eng = api.get_engine()
    if setup is not None:
        try:
            setup(eng, *(setup_args or ()))
        except Exception:
            record_log.error(
                "[standby] engine setup failed — serving without it",
                exc_info=True,
            )
    warm_s = max(
        0.05,
        config.get_int(config.SUPERVISE_STANDBY_WARM_MS, 2000) / 1e3,
    )

    def _rewarm() -> None:
        if eng.failover.armed and eng.failover.durable_path:
            try:
                eng.failover.restore_durable()
            except Exception:
                record_log.error(
                    "[standby] durable re-warm raised — keeping last "
                    "state", exc_info=True,
                )

    _rewarm()
    try:
        # The restore path may have probed already (try_recover); this
        # guarantees the jit cache is populated even when failover is
        # unarmed or the durable file does not exist yet.
        eng.failover.warm_probe()
    except Exception:
        record_log.error(
            "[standby] warm probe failed — reporting ready anyway "
            "(first flush will compile)", exc_info=True,
        )
    warm_boot_ms = (time.perf_counter() - t0) * 1e3
    try:
        conn.send(("ready", warm_boot_ms))
    except (OSError, ValueError, BrokenPipeError):
        return
    record_log.info(
        "[standby] warm and parked (pid %d, %.0f ms boot)",
        os.getpid(), warm_boot_ms,
    )
    while not stop.is_set():
        try:
            msg = conn.recv() if conn.poll(warm_s) else None
        except (EOFError, OSError):
            return  # supervisor died — the fleet dies with it
        if msg == "stop":
            eng.close()
            return
        if msg != "attach":
            if msg is None:
                _rewarm()
            continue
        # Promotion: final warm pass, re-arm the flight recorder (its
        # death sweep now runs AFTER the predecessor stopped writing
        # and honors the orderly-close marker), attach LAST so worker
        # re-assertions land on the restored world.
        _rewarm()
        if cap_override:
            config.set(config.CAPTURE_ENABLED, cap_override)
            try:
                from sentinel_tpu.runtime.capture import maybe_build_capture

                eng.capture = maybe_build_capture(eng)
            except Exception:
                record_log.error(
                    "[standby] capture re-arm failed — serving without "
                    "the flight recorder", exc_info=True,
                )
        t_att = time.perf_counter()
        IngestPlane(eng, handles=handles)
        attach_ms = (time.perf_counter() - t_att) * 1e3
        try:
            conn.send(("attached", attach_ms))
        except (OSError, ValueError, BrokenPipeError):
            pass
        record_log.info(
            "[standby] took over (pid %d, epoch %d, attach %.1f ms)",
            os.getpid(), eng.ipc_plane.engine_epoch, attach_ms,
        )
        raise SystemExit(_serve(eng, stop))
    eng.close()


class EngineSupervisor:
    """Keeps one engine child alive on the shared rings (see module
    doc). ``kill_engine()`` is the chaos hook the tests and the bench
    outage measurement use."""

    def __init__(
        self,
        setup=None,
        setup_args: Sequence[object] = (),
        n_workers: int = 0,
        prefix: Optional[str] = None,
    ) -> None:
        from sentinel_tpu.datasource.backoff import Backoff

        self._ctx = multiprocessing.get_context("spawn")
        if prefix is None:
            prefix = (config.get(config.IPC_SHM_PREFIX) or "").strip()
        if not prefix:
            prefix = f"stpu-{os.getpid()}-{int(time.time() * 1000) & 0xFFFFFF:x}"
        self.prefix = prefix
        # Children replay the runtime config; the prefix must be in it
        # so any path that re-reads config agrees on the names.
        config.set(config.IPC_SHM_PREFIX, prefix)
        self.handles = make_handles(self._ctx, prefix, n_workers)
        self._segs = create_segments(self.handles)
        self._setup = setup
        self._setup_args = tuple(setup_args or ())
        self._overrides = config.runtime_snapshot("sentinel.tpu.")
        self.restarts = 0
        self.restarts_max = max(
            0, config.get_int(config.SUPERVISE_RESTARTS_MAX, 0)
        )
        self._backoff = Backoff(
            base_s=max(1, config.get_int(config.SUPERVISE_BACKOFF_MS, 500))
            / 1e3,
            cap_s=max(
                1, config.get_int(config.SUPERVISE_BACKOFF_MAX_MS, 10000)
            ) / 1e3,
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.gave_up = False
        # Warm standby (sentinel.tpu.supervise.standby.enabled): one
        # pre-forked, compile-warmed engine child parked off-plane; on
        # primary death (or planned handoff) it attaches instead of a
        # cold respawn, and the NEXT standby is pre-forked immediately.
        self.standby_enabled = config.get_bool(config.SUPERVISE_STANDBY, False)
        self.standby_takeovers = 0
        self.handoffs = 0
        self.standby_warm_boot_ms: Optional[float] = None
        self.standby_attach_ms: Optional[float] = None
        self._standby: Optional[dict] = None
        # Promoted standbys keep their pipe alive here (the reader
        # thread still consumes the "attached" ack after promotion).
        self._retired: List[dict] = []
        self._proc = self._spawn_engine()
        if self.standby_enabled:
            self._standby = self._spawn_standby()
        self._watcher = threading.Thread(
            target=self._watch, name="sentinel-supervisor", daemon=True
        )
        self._watcher.start()

    # -- lifecycle ------------------------------------------------------
    def _spawn_engine(self):
        p = self._ctx.Process(
            target=engine_main,
            args=(self.handles, self._overrides, self._setup,
                  self._setup_args),
            daemon=True,
        )
        p.start()
        return p

    def _spawn_standby(self) -> dict:
        parent, child = self._ctx.Pipe()
        p = self._ctx.Process(
            target=standby_main,
            args=(self.handles, self._overrides, self._setup,
                  self._setup_args, child),
            daemon=True,
        )
        p.start()
        child.close()
        sb = {
            "proc": p, "conn": parent,
            "ready": threading.Event(), "attached": threading.Event(),
            "warm_ms": None, "attach_ms": None,
        }
        t = threading.Thread(
            target=self._standby_reader, args=(sb,),
            name="sentinel-standby-reader", daemon=True,
        )
        t.start()
        return sb

    def _standby_reader(self, sb: dict) -> None:
        """Owns all RECEIVES on one standby's pipe (sends may come
        from any thread) — runs until the child closes its end."""
        while True:
            try:
                msg = sb["conn"].recv()
            except (EOFError, OSError):
                return
            if not (isinstance(msg, tuple) and msg):
                continue
            if msg[0] == "ready":
                sb["warm_ms"] = msg[1]
                sb["ready"].set()
            elif msg[0] == "attached":
                sb["attach_ms"] = msg[1]
                sb["attached"].set()

    def _promote_standby(self, planned: bool, timeout_s: float = 180.0) -> bool:
        """Hand the rings to the warm standby: wait for its ready
        report (a standby still compiling is STILL faster than a cold
        respawn — its boot is already in progress), send ``attach``,
        adopt it as the serving child and pre-fork the next standby.
        False (→ caller falls back to the cold-respawn path) when no
        live standby exists."""
        from sentinel_tpu.utils.record_log import record_log

        sb = self._standby
        self._standby = None
        if sb is None:
            return False
        proc = sb["proc"]
        deadline = time.monotonic() + timeout_s
        while (
            proc.is_alive()
            and not sb["ready"].is_set()
            and time.monotonic() < deadline
            and not self._stop.is_set()
        ):
            time.sleep(0.01)
        if not proc.is_alive() or not sb["ready"].is_set():
            record_log.warn(
                "[supervise] standby unusable (alive=%s ready=%s) — "
                "falling back to cold respawn", proc.is_alive(),
                sb["ready"].is_set(),
            )
            if proc.is_alive():
                proc.terminate()
            try:
                sb["conn"].close()
            except OSError:
                pass
            return False
        try:
            sb["conn"].send("attach")
        except (OSError, ValueError, BrokenPipeError):
            return False
        self.standby_warm_boot_ms = sb["warm_ms"]
        with self._lock:
            self._proc = proc
        self._retired.append(sb)
        if sb["attached"].wait(timeout_s):
            self.standby_attach_ms = sb["attach_ms"]
        record_log.info(
            "[supervise] standby promoted (pid %d, %s, warm boot "
            "%.0f ms)", proc.pid, "planned handoff" if planned else
            "crash takeover", sb["warm_ms"] or -1.0,
        )
        self._standby = self._spawn_standby()
        return True

    def _watch(self) -> None:
        from sentinel_tpu.utils.record_log import record_log

        spawned_at = time.monotonic()
        while not self._stop.is_set():
            with self._lock:
                p = self._proc
            p.join(timeout=0.2)
            if p.is_alive():
                # A child that stayed up past the backoff cap ran
                # healthy: reset the streak so the NEXT incident pays
                # the base delay, not the accumulated lifetime cap
                # (crash-loop protection is per incident, not forever).
                if (
                    self._backoff.failures
                    and time.monotonic() - spawned_at > self._backoff.cap
                ):
                    self._backoff.reset()
                continue
            if self._stop.is_set():
                continue
            planned = p.exitcode == EXIT_HANDOFF
            if self.standby_enabled and self._promote_standby(planned):
                # A takeover is not a restart: the budget and the
                # backoff streak meter crash LOOPS of the cold path,
                # and a planned drain is not a crash at all.
                if planned:
                    self.handoffs += 1
                else:
                    self.standby_takeovers += 1
                spawned_at = time.monotonic()
                continue
            if (
                self.restarts_max
                and self.restarts >= self.restarts_max
            ):
                self.gave_up = True
                record_log.error(
                    "[supervise] engine died (exit %s) and the restart "
                    "budget (%d) is spent — giving up; workers stay on "
                    "the policy snapshot", p.exitcode, self.restarts_max,
                )
                return
            delay = self._backoff.next_delay()
            record_log.warn(
                "[supervise] engine died (exit %s) — restarting in "
                "%.2fs (restart #%d)", p.exitcode, delay,
                self.restarts + 1,
            )
            if self._stop.wait(delay):
                return
            with self._lock:
                if self._stop.is_set():
                    return
                self.restarts += 1
                self._proc = self._spawn_engine()
            spawned_at = time.monotonic()

    def spawn_context(self):
        """The supervisor's (spawn) mp context — queues for worker
        targets must come from here so they travel to descendants."""
        return self._ctx

    def spawn_worker(self, target, worker_id: int, args: Sequence[object] = ()):
        """One worker-mode child on slot ``worker_id`` (the supervised
        twin of ``api.run_workers``'s per-worker spawn; the supervisor
        owns the id space, so slots are assigned, not claimed)."""
        from sentinel_tpu.ipc import worker_mode

        p = self._ctx.Process(
            target=worker_mode.worker_main,
            args=(self.handles.channel(worker_id), worker_id,
                  self._overrides, target, tuple(args)),
            daemon=True,
        )
        p.start()
        return p

    # -- observability / chaos -----------------------------------------
    def engine_pid(self) -> Optional[int]:
        with self._lock:
            return self._proc.pid if self._proc.is_alive() else None

    def alive(self) -> bool:
        with self._lock:
            return self._proc.is_alive()

    def kill_engine(self) -> Optional[int]:
        """SIGKILL the current engine child (chaos/testing): no
        cleanup, no CLOSED word — exactly the failure the supervisor
        exists for. Returns the killed pid (None when already down)."""
        with self._lock:
            p = self._proc
        if not p.is_alive() or p.pid is None:
            return None
        os.kill(p.pid, signal.SIGKILL)
        return p.pid

    def wait_standby_ready(self, timeout_s: float = 180.0) -> bool:
        """Block until the CURRENT standby reports warm (rules
        loaded, kernels compiled, durable state restored). False when
        standby mode is off or the report never arrives."""
        sb = self._standby
        if sb is None:
            return False
        return sb["ready"].wait(timeout_s)

    def handoff(self, timeout_s: float = 120.0) -> bool:
        """Operator-triggered planned handoff (rolling upgrade /
        rule-table recompile served from standby): SIGUSR1 the serving
        engine — it drains (workers HOLD on the HANDOFF word), spills
        a final durable checkpoint and exits ``EXIT_HANDOFF``; the
        watcher promotes the warm standby. True once a DIFFERENT
        engine child is serving a fresh heartbeat."""
        with self._lock:
            p = self._proc
        if not p.is_alive() or p.pid is None:
            return False
        old_pid = p.pid
        try:
            os.kill(old_pid, signal.SIGUSR1)
        except OSError:
            return False
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline and not self._stop.is_set():
            with self._lock:
                cur = self._proc
            if (
                cur.pid != old_pid
                and cur.is_alive()
                and self.wait_engine_up(timeout_s=1.0)
            ):
                return True
            time.sleep(0.02)
        return False

    def wait_engine_up(self, timeout_s: float = 120.0) -> bool:
        """Block until the CURRENT engine child publishes a heartbeat
        (control header wall-ms fresh) — readiness, not liveness."""
        from sentinel_tpu.ipc.ring import ControlBlock, HEALTH_CLOSED, _wall_ms

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                ctl = ControlBlock(
                    f"{self.prefix}-ctl", self.handles.workers_max
                )
            except (OSError, ValueError):
                time.sleep(0.05)
                continue
            try:
                _epoch, health, _gen, wall = ctl.engine_view()
            finally:
                ctl.close()
            if wall and health != HEALTH_CLOSED and _wall_ms() - wall < 1000:
                return True
            time.sleep(0.05)
        return False

    def stop(self, timeout_s: float = 10.0) -> None:
        """Graceful shutdown: stop supervising, SIGTERM the engine
        child (it closes its engine cleanly), then unlink the
        segments."""
        self._stop.set()
        with self._lock:
            p = self._proc
        if p.is_alive() and p.pid is not None:
            try:
                os.kill(p.pid, signal.SIGTERM)
            except OSError:
                pass
        p.join(timeout_s)
        if p.is_alive():
            p.terminate()
            p.join(5.0)
        self._watcher.join(timeout=5.0)
        sb = self._standby
        self._standby = None
        if sb is not None:
            try:
                sb["conn"].send("stop")
            except (OSError, ValueError, BrokenPipeError):
                pass
            sb["proc"].join(timeout_s)
            if sb["proc"].is_alive():
                sb["proc"].terminate()
                sb["proc"].join(5.0)
            try:
                sb["conn"].close()
            except OSError:
                pass
        destroy_segments(self._segs)
        self._segs = []


def measure_restart_outage(
    setup,
    resource: str,
    prefix: Optional[str] = None,
    timeout_s: float = 180.0,
    entry_timeout_ms: int = 3000,
) -> dict:
    """The zero→kill→recover cycle as one measurement (shared by the
    bench ``ipc`` stage's ``restart_outage_ms`` column, the
    ``ipc_launch --smoke`` restart phase, and the chaos tests): start a
    supervised engine, probe from an IngestClient in THIS process until
    it serves device-backed verdicts, ``kill -9`` the engine child, and
    time how long callers stay on policy verdicts until the restarted
    engine serves again. Raises on no-recovery; callers treat that as a
    failed check."""
    from sentinel_tpu.ipc.worker import IngestClient

    sup = EngineSupervisor(setup=setup, n_workers=1, prefix=prefix)
    cli = None
    try:
        if not sup.wait_engine_up(timeout_s):
            raise RuntimeError("supervised engine never came up")
        cli = IngestClient(sup.handles.channel(0), 0)
        deadline = time.monotonic() + timeout_s
        while True:
            v = cli.entry(resource, timeout_ms=entry_timeout_ms)
            if v.admitted and not v.degraded:
                cli.exit(resource)
                break
            if time.monotonic() > deadline:
                raise RuntimeError("engine never served a live verdict")
            time.sleep(0.02)
        killed_pid = sup.kill_engine()
        t0 = time.monotonic()
        saw_dead = False
        policy_served = 0
        while time.monotonic() - t0 < timeout_s:
            v = cli.entry(resource, timeout_ms=entry_timeout_ms)
            if v.degraded or not v.admitted:
                # Policy-served (engine read dead) or the dead-world
                # frame's gen-gated shed from the NEW plane — both are
                # the outage window from the caller's seat.
                saw_dead = True
                policy_served += 1
            elif v.admitted:
                cli.exit(resource)
                if saw_dead:
                    outage_ms = (time.monotonic() - t0) * 1e3
                    # The reconnect (ledger re-assert) rides the beat
                    # loop and may land a tick AFTER the first live
                    # verdict — give it a moment so the returned count
                    # is deterministic for the chaos assertions.
                    grace = time.monotonic() + 10.0
                    while (
                        cli.counters.get("reconnects", 0) == 0
                        and time.monotonic() < grace
                    ):
                        time.sleep(0.05)
                    return {
                        "outage_ms": outage_ms,
                        "policy_served": policy_served,
                        "restarts": sup.restarts,
                        "reconnects": cli.counters.get("reconnects", 0),
                        "killed_pid": killed_pid,
                    }
            time.sleep(0.002)
        raise RuntimeError(
            f"no recovery within {timeout_s}s (restarts={sup.restarts})"
        )
    finally:
        if cli is not None:
            cli.close()
        sup.stop()


def measure_standby_outage(
    setup,
    resource: str,
    prefix: Optional[str] = None,
    timeout_s: float = 180.0,
    entry_timeout_ms: int = 30000,
) -> dict:
    """``measure_restart_outage`` with a warm standby armed: the same
    zero→kill→recover cycle, but the supervisor promotes the
    pre-forked standby instead of cold-booting — the measured outage is
    ≈ the detection window (`ipc.engine.dead.ms`), with the JAX-import
    and first-compile terms gone from the outage path. The caller must
    have set ``sentinel.tpu.supervise.standby.enabled`` (raises
    otherwise — measuring the cold path under this name would report a
    lie). Shared by the bench ``standby_outage_ms`` column, the
    ``ipc_launch --smoke`` standby phase, and the chaos tests."""
    from sentinel_tpu.ipc.worker import IngestClient

    sup = EngineSupervisor(setup=setup, n_workers=1, prefix=prefix)
    if not sup.standby_enabled:
        sup.stop()
        raise RuntimeError(
            "measure_standby_outage needs "
            "sentinel.tpu.supervise.standby.enabled=true"
        )
    cli = None
    try:
        if not sup.wait_engine_up(timeout_s):
            raise RuntimeError("supervised engine never came up")
        if not sup.wait_standby_ready(timeout_s):
            raise RuntimeError("standby never reported warm")
        cli = IngestClient(sup.handles.channel(0), 0)
        deadline = time.monotonic() + timeout_s
        while True:
            v = cli.entry(resource, timeout_ms=entry_timeout_ms)
            if v.admitted and not v.degraded:
                cli.exit(resource)
                break
            if time.monotonic() > deadline:
                raise RuntimeError("engine never served a live verdict")
            time.sleep(0.02)
        killed_pid = sup.kill_engine()
        t0 = time.monotonic()
        saw_dead = False
        policy_served = 0
        while time.monotonic() - t0 < timeout_s:
            v = cli.entry(resource, timeout_ms=entry_timeout_ms)
            if v.degraded or not v.admitted:
                saw_dead = True
                policy_served += 1
            elif v.admitted:
                cli.exit(resource)
                if saw_dead:
                    outage_ms = (time.monotonic() - t0) * 1e3
                    grace = time.monotonic() + 10.0
                    while (
                        cli.counters.get("reconnects", 0) == 0
                        and time.monotonic() < grace
                    ):
                        time.sleep(0.05)
                    return {
                        "outage_ms": outage_ms,
                        "policy_served": policy_served,
                        "standby_takeovers": sup.standby_takeovers,
                        "standby_warm_boot_ms": sup.standby_warm_boot_ms,
                        "standby_attach_ms": sup.standby_attach_ms,
                        "restarts": sup.restarts,
                        "reconnects": cli.counters.get("reconnects", 0),
                        "killed_pid": killed_pid,
                    }
            time.sleep(0.002)
        raise RuntimeError(
            f"no standby takeover within {timeout_s}s "
            f"(takeovers={sup.standby_takeovers})"
        )
    finally:
        if cli is not None:
            cli.close()
        sup.stop()


def measure_handoff_outage(
    setup,
    resource: str,
    prefix: Optional[str] = None,
    timeout_s: float = 180.0,
    entry_timeout_ms: int = 30000,
) -> dict:
    """One planned config-push handoff cycle under continuous probing:
    start a supervised engine with a warm standby, probe until live,
    trigger ``EngineSupervisor.handoff()`` and keep probing through the
    drain → detach → standby-attach window. Reports the worst gap
    between consecutive live verdicts (``handoff_outage_ms`` — callers
    were HELD, not failed, for that long) and the policy-served /
    non-admitted counts, which an orderly handoff keeps at ZERO."""
    from sentinel_tpu.ipc.worker import IngestClient

    sup = EngineSupervisor(setup=setup, n_workers=1, prefix=prefix)
    if not sup.standby_enabled:
        sup.stop()
        raise RuntimeError(
            "measure_handoff_outage needs "
            "sentinel.tpu.supervise.standby.enabled=true"
        )
    cli = None
    try:
        if not sup.wait_engine_up(timeout_s):
            raise RuntimeError("supervised engine never came up")
        if not sup.wait_standby_ready(timeout_s):
            raise RuntimeError("standby never reported warm")
        cli = IngestClient(sup.handles.channel(0), 0)
        deadline = time.monotonic() + timeout_s
        while True:
            v = cli.entry(resource, timeout_ms=entry_timeout_ms)
            if v.admitted and not v.degraded:
                cli.exit(resource)
                break
            if time.monotonic() > deadline:
                raise RuntimeError("engine never served a live verdict")
            time.sleep(0.02)
        pol0 = cli.counters.get("policy_served", 0)
        old_pid = sup.engine_pid()
        result: dict = {}
        ho = threading.Thread(
            target=lambda: result.update(ok=sup.handoff(timeout_s)),
            daemon=True,
        )
        t0 = time.monotonic()
        ho.start()
        last_live = t0
        max_gap = 0.0
        not_admitted = 0
        live_after = 0
        while time.monotonic() - t0 < timeout_s:
            v = cli.entry(resource, timeout_ms=entry_timeout_ms)
            now = time.monotonic()
            if v.admitted and not v.degraded:
                cli.exit(resource)
                max_gap = max(max_gap, now - last_live)
                last_live = now
                if not ho.is_alive() and sup.engine_pid() not in (
                    None, old_pid
                ):
                    live_after += 1
                    if live_after >= 3:
                        break
            else:
                not_admitted += 1
            time.sleep(0.002)
        ho.join(timeout_s)
        if not result.get("ok"):
            raise RuntimeError(
                f"handoff never completed (handoffs={sup.handoffs})"
            )
        return {
            "handoff_outage_ms": max_gap * 1e3,
            "policy_served": cli.counters.get("policy_served", 0) - pol0,
            "not_admitted": not_admitted,
            "handoffs": sup.handoffs,
            "standby_warm_boot_ms": sup.standby_warm_boot_ms,
            "standby_attach_ms": sup.standby_attach_ms,
            "reconnects": cli.counters.get("reconnects", 0),
        }
    finally:
        if cli is not None:
            cli.close()
        sup.stop()
