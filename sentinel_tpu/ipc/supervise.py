"""Engine supervision & warm hot-restart for the multi-process plane.

PR 13/14 made one engine process the spine for N worker processes —
and therefore the single point of failure: an engine death left every
worker serving *static* policy-snapshot verdicts forever. This module
closes the loop (the Envoy hot-restart lineage: warm handoff, not cold
start):

* the **supervisor** (this process) owns the named shared-memory
  segments and the cross-process primitives (the MPSC claim lock and
  the adaptive-wakeup doorbells — they cannot live in shared memory,
  so they must belong to a process that OUTLIVES any one engine);
* the **engine child** builds its Engine, loads rules (the ``setup``
  callable), warm-starts from the durable checkpoint
  (``sentinel.tpu.failover.checkpoint.path`` →
  ``FailoverManager.restore_durable``), then attaches an
  :class:`~sentinel_tpu.ipc.plane.IngestPlane` to the EXISTING rings —
  bumping the control header's engine-boot epoch;
* **workers** are ordinary worker-mode children: when the engine dies
  they serve the failover-policy snapshot, and when the epoch bumps
  they re-intern, re-assert their live-admission ledgers and replay
  buffered completions (ipc/worker.py reconnect protocol);
* a crashed engine child is respawned on the shared
  :class:`~sentinel_tpu.datasource.backoff.Backoff`
  (``sentinel.tpu.supervise.backoff.{ms,max.ms}``), bounded by
  ``sentinel.tpu.supervise.restarts.max`` (0 = unlimited).

The public faces are ``api.run_engine_supervised`` (embedders) and
``tools/ipc_launch.py --supervise`` (CLI).
"""

from __future__ import annotations

import multiprocessing
import os
import signal
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from sentinel_tpu.utils.config import config


@dataclass
class PlaneHandles:
    """Everything an engine child (and the worker channels) need to
    share one set of named segments across engine restarts. Picklable
    through ``multiprocessing`` spawn — the lock/semaphores travel via
    mp's own reduction, so every consumer must be a DESCENDANT of the
    process that built this (the supervisor)."""

    prefix: str
    workers_max: int
    ring_slots: int
    slot_bytes: int
    resp_slots: int
    n_workers: int
    request_lock: object = field(repr=False, default=None)
    request_doorbell: object = field(repr=False, default=None)
    response_doorbells: Optional[List[object]] = field(
        repr=False, default=None
    )

    def channel(self, worker_id: int):
        """The worker-side attach record for one slot — the supervised
        twin of ``IngestPlane.channel`` (names are deterministic, so no
        plane object is needed here)."""
        from sentinel_tpu.ipc.worker import PlaneChannel

        bells = self.response_doorbells or []
        return PlaneChannel(
            control_name=f"{self.prefix}-ctl",
            request_name=f"{self.prefix}-req",
            response_name=f"{self.prefix}-resp{worker_id}",
            ring_slots=self.ring_slots,
            slot_bytes=self.slot_bytes,
            resp_slots=self.resp_slots,
            workers_max=self.workers_max,
            request_lock=self.request_lock,
            request_doorbell=self.request_doorbell,
            response_doorbell=(
                bells[worker_id] if worker_id < len(bells) else None
            ),
        )


def make_handles(ctx, prefix: str, n_workers: int) -> PlaneHandles:
    """Build the shared primitives from the current config (the
    supervisor side; geometry keys replay into every child)."""
    wake = (config.get(config.IPC_WAKEUP) or "sleep").strip().lower()
    adaptive = wake == "adaptive"
    workers_max = max(1, config.get_int(config.IPC_WORKERS_MAX, 8))
    return PlaneHandles(
        prefix=prefix,
        workers_max=workers_max,
        ring_slots=config.get_int(config.IPC_RING_SLOTS, 1024),
        slot_bytes=max(1024, config.get_int(config.IPC_SLOT_BYTES, 16384)),
        resp_slots=config.get_int(config.IPC_RESP_SLOTS, 1024),
        n_workers=max(0, min(n_workers, workers_max)),
        request_lock=ctx.Lock(),
        request_doorbell=ctx.Semaphore(0) if adaptive else None,
        response_doorbells=(
            [ctx.Semaphore(0) for _ in range(workers_max)]
            if adaptive else None
        ),
    )


def _unlink_stale(name: str) -> None:
    """Remove a leftover segment from a DEAD supervisor incarnation.
    Safe by construction: the engine child and all workers are daemon
    children of the supervisor, so a crashed supervisor takes its whole
    fleet with it — nothing live can still be mapped to these names."""
    from multiprocessing import shared_memory

    try:
        s = shared_memory.SharedMemory(name)
    except (FileNotFoundError, OSError, ValueError):
        return
    try:
        s.close()
        s.unlink()
    except OSError:
        pass


def create_segments(handles: PlaneHandles):
    """Pre-create every named segment from the SUPERVISOR so (a) they
    outlive any one engine process and (b) workers can attach before
    the first engine is even up. A segment left behind by a CRASHED
    supervisor (its own kill -9 is inside this PR's failure domain) is
    unlinked and recreated fresh — the old fleet died with it. Returns
    the owner objects — keep them alive; ``destroy_segments`` unlinks
    at final shutdown."""
    from sentinel_tpu.ipc.ring import ControlBlock, ShmRing

    def fresh(factory, name):
        try:
            return factory()
        except FileExistsError:
            _unlink_stale(name)
            return factory()

    segs = [fresh(
        lambda: ControlBlock(
            f"{handles.prefix}-ctl", handles.workers_max, create=True
        ),
        f"{handles.prefix}-ctl",
    )]
    segs.append(fresh(
        lambda: ShmRing(
            f"{handles.prefix}-req", handles.ring_slots,
            handles.slot_bytes, create=True,
        ),
        f"{handles.prefix}-req",
    ))
    for wid in range(handles.n_workers):
        name = f"{handles.prefix}-resp{wid}"
        segs.append(fresh(
            lambda name=name: ShmRing(
                name, handles.resp_slots, handles.slot_bytes, create=True
            ),
            name,
        ))
    return segs


def destroy_segments(segs) -> None:
    for s in segs:
        try:
            s.destroy()
        except Exception:
            pass


def engine_main(handles: PlaneHandles, overrides, setup, setup_args) -> None:
    """Spawn target: one engine child's whole life. Top-level so
    ``multiprocessing`` spawn children import it by name.

    Order matters: rules first (``setup``), then the durable
    warm-start (restore wants the rule indexes compiled so the
    fingerprints can match), and the plane LAST — workers reconnect
    only once the warm state is installed, so their ledger
    re-assertions land on the restored world, never a half-built one."""
    for k, v in (overrides or {}).items():
        config.set(k, v)
    # This child constructs its plane explicitly from the handles — a
    # replayed ipc.enabled=true must not auto-start a second, anonymous
    # plane inside Engine.__init__.
    config.set(config.IPC_ENABLED, "false")
    from sentinel_tpu.core import api
    from sentinel_tpu.ipc.plane import IngestPlane
    from sentinel_tpu.utils.record_log import record_log

    stop = threading.Event()

    def _on_term(_sig, _frm):
        stop.set()

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    eng = api.get_engine()
    if setup is not None:
        try:
            setup(eng, *(setup_args or ()))
        except Exception:
            record_log.error(
                "[supervise] engine setup failed — serving without it",
                exc_info=True,
            )
    if eng.failover.armed and eng.failover.durable_path:
        try:
            eng.failover.restore_durable()
        except Exception:
            # restore_durable itself never raises by contract; this is
            # the last-resort guard — a warm start is an optimization,
            # never a liveness requirement.
            record_log.error(
                "[supervise] durable restore raised — cold start",
                exc_info=True,
            )
    IngestPlane(eng, handles=handles)
    record_log.info(
        "[supervise] engine child up (pid %d, epoch %d)",
        os.getpid(), eng.ipc_plane.engine_epoch,
    )
    while not stop.is_set():
        stop.wait(0.2)
    eng.close()


class EngineSupervisor:
    """Keeps one engine child alive on the shared rings (see module
    doc). ``kill_engine()`` is the chaos hook the tests and the bench
    outage measurement use."""

    def __init__(
        self,
        setup=None,
        setup_args: Sequence[object] = (),
        n_workers: int = 0,
        prefix: Optional[str] = None,
    ) -> None:
        from sentinel_tpu.datasource.backoff import Backoff

        self._ctx = multiprocessing.get_context("spawn")
        if prefix is None:
            prefix = (config.get(config.IPC_SHM_PREFIX) or "").strip()
        if not prefix:
            prefix = f"stpu-{os.getpid()}-{int(time.time() * 1000) & 0xFFFFFF:x}"
        self.prefix = prefix
        # Children replay the runtime config; the prefix must be in it
        # so any path that re-reads config agrees on the names.
        config.set(config.IPC_SHM_PREFIX, prefix)
        self.handles = make_handles(self._ctx, prefix, n_workers)
        self._segs = create_segments(self.handles)
        self._setup = setup
        self._setup_args = tuple(setup_args or ())
        self._overrides = config.runtime_snapshot("sentinel.tpu.")
        self.restarts = 0
        self.restarts_max = max(
            0, config.get_int(config.SUPERVISE_RESTARTS_MAX, 0)
        )
        self._backoff = Backoff(
            base_s=max(1, config.get_int(config.SUPERVISE_BACKOFF_MS, 500))
            / 1e3,
            cap_s=max(
                1, config.get_int(config.SUPERVISE_BACKOFF_MAX_MS, 10000)
            ) / 1e3,
        )
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.gave_up = False
        self._proc = self._spawn_engine()
        self._watcher = threading.Thread(
            target=self._watch, name="sentinel-supervisor", daemon=True
        )
        self._watcher.start()

    # -- lifecycle ------------------------------------------------------
    def _spawn_engine(self):
        p = self._ctx.Process(
            target=engine_main,
            args=(self.handles, self._overrides, self._setup,
                  self._setup_args),
            daemon=True,
        )
        p.start()
        return p

    def _watch(self) -> None:
        from sentinel_tpu.utils.record_log import record_log

        spawned_at = time.monotonic()
        while not self._stop.is_set():
            with self._lock:
                p = self._proc
            p.join(timeout=0.2)
            if p.is_alive():
                # A child that stayed up past the backoff cap ran
                # healthy: reset the streak so the NEXT incident pays
                # the base delay, not the accumulated lifetime cap
                # (crash-loop protection is per incident, not forever).
                if (
                    self._backoff.failures
                    and time.monotonic() - spawned_at > self._backoff.cap
                ):
                    self._backoff.reset()
                continue
            if self._stop.is_set():
                continue
            if (
                self.restarts_max
                and self.restarts >= self.restarts_max
            ):
                self.gave_up = True
                record_log.error(
                    "[supervise] engine died (exit %s) and the restart "
                    "budget (%d) is spent — giving up; workers stay on "
                    "the policy snapshot", p.exitcode, self.restarts_max,
                )
                return
            delay = self._backoff.next_delay()
            record_log.warn(
                "[supervise] engine died (exit %s) — restarting in "
                "%.2fs (restart #%d)", p.exitcode, delay,
                self.restarts + 1,
            )
            if self._stop.wait(delay):
                return
            with self._lock:
                if self._stop.is_set():
                    return
                self.restarts += 1
                self._proc = self._spawn_engine()
            spawned_at = time.monotonic()

    def spawn_context(self):
        """The supervisor's (spawn) mp context — queues for worker
        targets must come from here so they travel to descendants."""
        return self._ctx

    def spawn_worker(self, target, worker_id: int, args: Sequence[object] = ()):
        """One worker-mode child on slot ``worker_id`` (the supervised
        twin of ``api.run_workers``'s per-worker spawn; the supervisor
        owns the id space, so slots are assigned, not claimed)."""
        from sentinel_tpu.ipc import worker_mode

        p = self._ctx.Process(
            target=worker_mode.worker_main,
            args=(self.handles.channel(worker_id), worker_id,
                  self._overrides, target, tuple(args)),
            daemon=True,
        )
        p.start()
        return p

    # -- observability / chaos -----------------------------------------
    def engine_pid(self) -> Optional[int]:
        with self._lock:
            return self._proc.pid if self._proc.is_alive() else None

    def alive(self) -> bool:
        with self._lock:
            return self._proc.is_alive()

    def kill_engine(self) -> Optional[int]:
        """SIGKILL the current engine child (chaos/testing): no
        cleanup, no CLOSED word — exactly the failure the supervisor
        exists for. Returns the killed pid (None when already down)."""
        with self._lock:
            p = self._proc
        if not p.is_alive() or p.pid is None:
            return None
        os.kill(p.pid, signal.SIGKILL)
        return p.pid

    def wait_engine_up(self, timeout_s: float = 120.0) -> bool:
        """Block until the CURRENT engine child publishes a heartbeat
        (control header wall-ms fresh) — readiness, not liveness."""
        from sentinel_tpu.ipc.ring import ControlBlock, HEALTH_CLOSED, _wall_ms

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                ctl = ControlBlock(
                    f"{self.prefix}-ctl", self.handles.workers_max
                )
            except (OSError, ValueError):
                time.sleep(0.05)
                continue
            try:
                _epoch, health, _gen, wall = ctl.engine_view()
            finally:
                ctl.close()
            if wall and health != HEALTH_CLOSED and _wall_ms() - wall < 1000:
                return True
            time.sleep(0.05)
        return False

    def stop(self, timeout_s: float = 10.0) -> None:
        """Graceful shutdown: stop supervising, SIGTERM the engine
        child (it closes its engine cleanly), then unlink the
        segments."""
        self._stop.set()
        with self._lock:
            p = self._proc
        if p.is_alive() and p.pid is not None:
            try:
                os.kill(p.pid, signal.SIGTERM)
            except OSError:
                pass
        p.join(timeout_s)
        if p.is_alive():
            p.terminate()
            p.join(5.0)
        self._watcher.join(timeout=5.0)
        destroy_segments(self._segs)
        self._segs = []


def measure_restart_outage(
    setup,
    resource: str,
    prefix: Optional[str] = None,
    timeout_s: float = 180.0,
    entry_timeout_ms: int = 3000,
) -> dict:
    """The zero→kill→recover cycle as one measurement (shared by the
    bench ``ipc`` stage's ``restart_outage_ms`` column, the
    ``ipc_launch --smoke`` restart phase, and the chaos tests): start a
    supervised engine, probe from an IngestClient in THIS process until
    it serves device-backed verdicts, ``kill -9`` the engine child, and
    time how long callers stay on policy verdicts until the restarted
    engine serves again. Raises on no-recovery; callers treat that as a
    failed check."""
    from sentinel_tpu.ipc.worker import IngestClient

    sup = EngineSupervisor(setup=setup, n_workers=1, prefix=prefix)
    cli = None
    try:
        if not sup.wait_engine_up(timeout_s):
            raise RuntimeError("supervised engine never came up")
        cli = IngestClient(sup.handles.channel(0), 0)
        deadline = time.monotonic() + timeout_s
        while True:
            v = cli.entry(resource, timeout_ms=entry_timeout_ms)
            if v.admitted and not v.degraded:
                cli.exit(resource)
                break
            if time.monotonic() > deadline:
                raise RuntimeError("engine never served a live verdict")
            time.sleep(0.02)
        killed_pid = sup.kill_engine()
        t0 = time.monotonic()
        saw_dead = False
        policy_served = 0
        while time.monotonic() - t0 < timeout_s:
            v = cli.entry(resource, timeout_ms=entry_timeout_ms)
            if v.degraded or not v.admitted:
                # Policy-served (engine read dead) or the dead-world
                # frame's gen-gated shed from the NEW plane — both are
                # the outage window from the caller's seat.
                saw_dead = True
                policy_served += 1
            elif v.admitted:
                cli.exit(resource)
                if saw_dead:
                    outage_ms = (time.monotonic() - t0) * 1e3
                    # The reconnect (ledger re-assert) rides the beat
                    # loop and may land a tick AFTER the first live
                    # verdict — give it a moment so the returned count
                    # is deterministic for the chaos assertions.
                    grace = time.monotonic() + 10.0
                    while (
                        cli.counters.get("reconnects", 0) == 0
                        and time.monotonic() < grace
                    ):
                        time.sleep(0.05)
                    return {
                        "outage_ms": outage_ms,
                        "policy_served": policy_served,
                        "restarts": sup.restarts,
                        "reconnects": cli.counters.get("reconnects", 0),
                        "killed_pid": killed_pid,
                    }
            time.sleep(0.002)
        raise RuntimeError(
            f"no recovery within {timeout_s}s (restarts={sup.restarts})"
        )
    finally:
        if cli is not None:
            cli.close()
        sup.stop()
